"""Sharded-optimizer data parallelism (parity: the reference's Reduce mode —
`ReduceSSAGraphBuilder` multi_devices_graph_pass.h:164 /
details/reduce_op_handle.cc, SURVEY §2.3 P2: "each param's grad reduced to
one owner device, updated there, then broadcast — ZeRO-1-like ancestor").

TPU-native: inside shard_map over the dp axis each gradient leaf is
reduce-scattered along its leading dimension, the optimizer update runs on
the rank-local 1/n slice of (param, m, v), and updated slices all-gather
back — optimizer state is born sharded, never materialized whole, exactly
the memory the pserver param-blocking bought the reference.

Bucketed mode (Megatron-LM DDP parity, docs/MIXED_PRECISION.md): with
`bucket_mb` set (or $PTPU_AMP_BUCKET_MB in the environment), per-parameter
gradients are flattened and coalesced into a few large same-dtype buckets
before the collective — `grad_dtype=jnp.bfloat16` then moves HALF the
reduce-scatter bytes in a handful of large transfers instead of one small
fp32 collective per parameter. Optimizer state (m/v) stays fp32, laid out
flat per bucket and dp-sharded; the update math is identical to the
per-leaf path (the gradient is cast to fp32 exactly once, after the
collective).
"""

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.jax_compat import shard_map


def _pad_leading(x, n):
    pad = (-x.shape[0]) % n
    if pad:
        x = jnp.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1))
    return x


class ShardedAdam:
    """Adam with dp-sharded moments (ZeRO-1 / Reduce-mode parity).

    bucket_mb: flatten gradients into same-dtype buckets of this many
    MiB for the reduce-scatter (None = read $PTPU_AMP_BUCKET_MB; 0 or an
    unset environment = the legacy one-collective-per-leaf path).
    grad_dtype: dtype the gradients are cast to BEFORE the collective
    (e.g. jnp.bfloat16 under AMP — half the bytes on the wire); None
    keeps each gradient's own dtype."""

    def __init__(self, learning_rate=1e-3, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, axis_name="dp", grad_dtype=None,
                 bucket_mb=None):
        self.lr = learning_rate
        self.b1, self.b2, self.eps = beta1, beta2, epsilon
        self.axis = axis_name
        self.grad_dtype = grad_dtype
        self.bucket_mb = bucket_mb
        self._layout = None
        self._bucketed = None  # resolved by init_state; None = not yet

    def _bucket_bytes(self):
        from .. import amp

        if self.bucket_mb is not None:
            return amp.mb_to_bucket_bytes(self.bucket_mb)
        return amp.bucket_bytes_from_env(default_mb=None)

    # ------------------------------------------------------------------
    def init_state(self, params, mesh):
        """m/v pytrees sharded over dp: per-leaf leading-dim shards in
        the legacy path, flat per-BUCKET shards in bucketed mode. The
        mode is LATCHED here — make_step follows this decision even if
        the environment changes in between (state layout and step
        function must agree)."""
        bb = self._bucket_bytes()
        self._bucketed = bool(bb)
        n = mesh.shape[self.axis]
        if bb:
            from .. import amp

            flat, _ = jax.tree.flatten(params)
            gdt = self.grad_dtype if self.grad_dtype is not None \
                else jnp.float32
            self._layout = amp.plan_buckets(flat, bb, pad_multiple=n,
                                            dtype=gdt)
            sh = NamedSharding(mesh, P(self.axis))

            def zeros_flat(b):
                return jax.device_put(jnp.zeros((b.padded,), jnp.float32),
                                      sh)

            return {"m": [zeros_flat(b) for b in self._layout],
                    "v": [zeros_flat(b) for b in self._layout],
                    "step": jnp.zeros((), jnp.int32)}

        def zeros_sharded(p):
            shape = ((p.shape[0] + (-p.shape[0]) % n),) + p.shape[1:]
            z = jnp.zeros(shape, jnp.float32)
            return jax.device_put(
                z, jax.sharding.NamedSharding(mesh, P(self.axis)))

        return {"m": jax.tree.map(zeros_sharded, params),
                "v": jax.tree.map(zeros_sharded, params),
                "step": jnp.zeros((), jnp.int32)}

    # ------------------------------------------------------------------
    def _local_update(self, g_shard, p_shard, m, v, t):
        m = self.b1 * m + (1 - self.b1) * g_shard
        v = self.b2 * v + (1 - self.b2) * jnp.square(g_shard)
        mhat = m / (1 - self.b1 ** t)
        vhat = v / (1 - self.b2 ** t)
        p_new = p_shard - self.lr * mhat / (jnp.sqrt(vhat) + self.eps)
        return p_new, m, v

    def make_step(self, mesh, loss_fn):
        """jit-compiled (params, state, *batch) -> (params, state, loss)
        with grads reduce-scattered and updates computed on local shards."""
        bucketed = self._bucketed if self._bucketed is not None \
            else bool(self._bucket_bytes())
        if bucketed:
            return self._make_step_bucketed(mesh, loss_fn)
        axis = self.axis
        n = mesh.shape[axis]

        def step(params, state, *batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, *batch)
            t = state["step"] + 1

            def upd(p, g, m, v):
                # grad_dtype applies BEFORE the collective in this path
                # too (halved wire bytes); the fp32 cast moves to the
                # local shard, after the reduce-scatter
                gdt = self.grad_dtype if self.grad_dtype is not None \
                    else jnp.float32
                gp = _pad_leading(g.astype(gdt), n)
                pp = _pad_leading(p.astype(jnp.float32), n)

                def inner(gp, pp, m, v):
                    # mean-reduce + scatter the grad to its owner rank
                    gs = jax.lax.psum_scatter(
                        gp, axis, scatter_dimension=0, tiled=True) / n
                    p_new, m, v = self._local_update(
                        gs.astype(jnp.float32), pp, m, v,
                        t.astype(jnp.float32))
                    # broadcast updated slices back (BCastParamsToDevices
                    # parity, parallel_executor.cc:434)
                    p_full = jax.lax.all_gather(p_new, axis, axis=0,
                                                tiled=True)
                    return p_full, m, v

                spec_full = P()
                spec_shard = P(axis)
                p_full, m, v = shard_map(
                    inner, mesh=mesh,
                    in_specs=(spec_full, spec_shard, spec_shard, spec_shard),
                    out_specs=(spec_full, spec_shard, spec_shard),
                    check_vma=False)(gp, pp, m, v)
                return p_full[: p.shape[0]].astype(p.dtype), m, v

            flat_p, tdef = jax.tree.flatten(params)
            flat_g = tdef.flatten_up_to(grads)
            flat_m = tdef.flatten_up_to(state["m"])
            flat_v = tdef.flatten_up_to(state["v"])
            out = [upd(p, g, m, v)
                   for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v)]
            new_p = tdef.unflatten([o[0] for o in out])
            new_state = {"m": tdef.unflatten([o[1] for o in out]),
                         "v": tdef.unflatten([o[2] for o in out]),
                         "step": t}
            return new_p, new_state, loss

        return jax.jit(step, donate_argnums=(0, 1))

    def _make_step_bucketed(self, mesh, loss_fn):
        """Same update math, but the reduce-scatter moves a few large
        flattened buckets (in grad_dtype) instead of one collective per
        leaf. Call init_state first — it plans the bucket layout."""
        from .. import amp

        if self._layout is None:
            raise RuntimeError(
                "bucketed ShardedAdam: call init_state(params, mesh) "
                "before make_step (it plans the bucket layout)")
        axis = self.axis
        n = mesh.shape[axis]
        layout = self._layout
        spec_full = P()
        spec_shard = P(axis)

        def step(params, state, *batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, *batch)
            t = state["step"] + 1
            flat_p, tdef = jax.tree.flatten(params)
            flat_g = tdef.flatten_up_to(grads)
            new_flat = list(flat_p)
            new_m, new_v = [], []
            for k, b in enumerate(layout):
                gbuf = amp.flatten_bucket(b, flat_g)
                # params flatten in fp32 REGARDLESS of the collective
                # dtype — rounding the master copy through bf16 would
                # destroy the mixed-precision contract
                pbuf = amp.flatten_bucket(b, flat_p, dtype=jnp.float32)

                def inner(gb, pb, m, v):
                    # ONE large low-precision reduce-scatter per bucket;
                    # the fp32 cast happens once, on the local shard
                    gs = jax.lax.psum_scatter(
                        gb, axis, scatter_dimension=0, tiled=True) / n
                    p_new, m, v = self._local_update(
                        gs.astype(jnp.float32), pb, m, v,
                        t.astype(jnp.float32))
                    p_full = jax.lax.all_gather(p_new, axis, axis=0,
                                                tiled=True)
                    return p_full, m, v

                p_full, mb, vb = shard_map(
                    inner, mesh=mesh,
                    in_specs=(spec_full, spec_shard, spec_shard,
                              spec_shard),
                    out_specs=(spec_full, spec_shard, spec_shard),
                    check_vma=False)(gbuf, pbuf, state["m"][k],
                                     state["v"][k])
                for i, seg in amp.unflatten_bucket(b, p_full,
                                                   flat_p).items():
                    new_flat[i] = seg
                new_m.append(mb)
                new_v.append(vb)
            return (tdef.unflatten(new_flat),
                    {"m": new_m, "v": new_v, "step": t}, loss)

        return jax.jit(step, donate_argnums=(0, 1))
