"""SPMD Transformer trainer: dp + pp + tp + sp + ep over one shard_map.

This is the TPU-native replacement for everything the reference built with
ParallelExecutor/NCCL/transpilers (SURVEY §2.3) *plus* the parallel modes
the 2019 reference lacked (tensor/pipeline/sequence/expert parallelism are
new design, per SURVEY §5.7).

Mesh: ("dp", "pp", "tp").
- dp  — data parallel: batch sharded; per-leaf gradient psum over replicated
        axes replaces AllReduceOpHandle (details/all_reduce_op_handle.cc:91).
- pp  — pipeline parallel: layers sharded on their leading [L] axis; GPipe
        microbatch schedule as a lax.scan whose carry rotates activations
        through the stage ring with ppermute (ICI neighbor exchange).
- tp  — tensor parallel (Megatron-style): attention heads + FFN hidden
        sharded; partial outputs reduce via reduce_scatter.
- sp  — sequence parallel on the SAME tp axis: the residual stream between
        blocks is sequence-sharded [B, T/tp, D]; all_gather before each
        matmul, reduce_scatter after — LN/dropout/residual math never
        duplicates across tp.
- ep  — expert parallel on the dp axis: MoE FFN tokens exchanged with
        all_to_all, one expert group per dp rank.

Gradients: jax.grad of the rank-local masked loss inside shard_map; the
collective transposes (all_gather ↔ reduce_scatter, ppermute ↔ reverse
ppermute, all_to_all ↔ all_to_all) route cross-rank cotangents, so the
result is the gradient of the GLOBAL loss wrt local shards. Each leaf is
then psummed over exactly the mesh axes it is replicated on (the axes
absent from its PartitionSpec) — the sharding-aware generalization of the
reference's single gradient allreduce.
"""

import dataclasses
import functools
import math
from typing import Any, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ..core.jax_compat import axis_index as _axis_index, shard_map

from ..models import transformer as T


# ---------------------------------------------------------------------------
# sharding specs
# ---------------------------------------------------------------------------


def param_specs(cfg: T.TransformerConfig):
    """PartitionSpec pytree congruent with init_params output."""
    specs = {
        "embed": P(None, None),
        "pos_embed": P(None, None),
        "final_ln_scale": P(None),
        "final_ln_bias": P(None),
        "layers": {
            "ln1_scale": P("pp", None),
            "ln1_bias": P("pp", None),
            "wqkv": P("pp", None, None, "tp", None),
            "wo": P("pp", "tp", None, None),
            "ln2_scale": P("pp", None),
            "ln2_bias": P("pp", None),
            "w1": P("pp", None, "tp"),
            "b1": P("pp", "tp"),
            "w2": P("pp", "tp", None),
            "b2": P("pp", None),
        },
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, None)
    if cfg.n_experts:
        specs["moe"] = {
            "router": P(None, None),
            "w1": P("dp", None, None),
            "w2": P("dp", None, None),
        }
    return specs


def _replicated_axes(spec, mesh_axes=("dp", "pp", "tp")):
    used = set(a for a in spec if a is not None)
    return tuple(a for a in mesh_axes if a not in used)


# ---------------------------------------------------------------------------
# rank-local building blocks (run inside shard_map)
# ---------------------------------------------------------------------------


def _block_sp(lp, h_s, cfg):
    """One transformer block on a sequence-sharded residual stream h_s
    [B, T/tp, D]. all_gather('tp') before matmuls, reduce_scatter after —
    Megatron-SP seams."""
    dtype = cfg.dtype

    x = T.layer_norm(h_s, lp["ln1_scale"], lp["ln1_bias"])
    x_full = jax.lax.all_gather(x, "tp", axis=1, tiled=True)  # [B, T, D]
    attn_partial = T.attention_block(lp, x_full, dtype)
    attn_s = jax.lax.psum_scatter(attn_partial, "tp", scatter_dimension=1,
                                  tiled=True)
    h_s = h_s + attn_s

    x = T.layer_norm(h_s, lp["ln2_scale"], lp["ln2_bias"])
    x_full = jax.lax.all_gather(x, "tp", axis=1, tiled=True)
    ffn_partial = T.ffn_block(lp, x_full, dtype)
    ffn_s = jax.lax.psum_scatter(ffn_partial, "tp", scatter_dimension=1,
                                 tiled=True)
    # b2 is tp-replicated; add once on the scattered output
    h_s = h_s + ffn_s + lp["b2"].astype(dtype)
    return h_s


def _moe_block(mp, h_s, cfg):
    """Top-1 switch MoE on the local token shard; experts sharded over the
    dp axis (expert parallelism). h_s: [B, t, D] -> same."""
    dtype = cfg.dtype
    E = cfg.n_experts
    ep = jax.lax.psum(1, "dp")  # ep group size
    e_local = E // ep
    B, t, D = h_s.shape
    N = B * t
    x = h_s.reshape(N, D)

    gates = jax.nn.softmax(
        jnp.einsum("nd,de->ne", x.astype(jnp.float32),
                   mp["router"].astype(jnp.float32)))
    expert = jnp.argmax(gates, axis=-1)  # [N]
    gate = jnp.take_along_axis(gates, expert[:, None], axis=-1)[:, 0]

    cap = int(cfg.expert_capacity_factor * N / E) + 1
    # position of each token within its expert's capacity
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.int32)  # [N, E]
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1  # [N, E], -1 elsewhere
    pos1 = pos.max(axis=-1)  # [N]
    keep = pos1 < cap
    # dispatch [E, cap, D]
    disp = jnp.zeros((E, cap, D), dtype)
    idx_e = jnp.where(keep, expert, 0)
    idx_c = jnp.where(keep, pos1, 0)
    disp = disp.at[idx_e, idx_c].add(
        jnp.where(keep[:, None], x, 0).astype(dtype))
    # all_to_all over dp ("transpose"): send expert-group r's slice to rank
    # r; axis 0 of the result indexes the SOURCE rank.
    disp = disp.reshape(ep, e_local, cap, D)
    recv = jax.lax.all_to_all(disp, "dp", split_axis=0, concat_axis=0)
    toks = recv.transpose(1, 0, 2, 3).reshape(e_local, ep * cap, D)
    # expert FFN (local experts)
    a = jnp.einsum("ecd,edf->ecf", toks, mp["w1"].astype(dtype))
    a = jax.nn.gelu(a)
    out = jnp.einsum("ecf,efd->ecd", a, mp["w2"].astype(dtype))
    # route back: inverse all_to_all
    out = out.reshape(e_local, ep, cap, D).transpose(1, 0, 2, 3)
    back = jax.lax.all_to_all(out, "dp", split_axis=0, concat_axis=0)
    back = back.reshape(E, cap, D)
    # combine
    y = back[idx_e, idx_c]  # [N, D]
    y = jnp.where(keep[:, None], y, 0).astype(jnp.float32)
    y = y * gate[:, None]
    return h_s + y.reshape(B, t, D).astype(dtype)


def _stage_fn(stage_params, moe_params, h_s, cfg, layers_per_stage):
    """Run this pp rank's slice of layers (+ optional MoE) on a
    seq-sharded activation."""
    body = functools.partial(_block_sp, cfg=cfg)
    if cfg.remat:
        body = jax.checkpoint(body)
    for i in range(layers_per_stage):
        lp = jax.tree.map(lambda x: x[i], stage_params)
        h_s = body(lp, h_s)
    if moe_params is not None:
        mb = functools.partial(_moe_block, cfg=cfg)
        if cfg.remat:
            mb = jax.checkpoint(mb)
        h_s = mb(moe_params, h_s)
    return h_s


# ---------------------------------------------------------------------------
# the SPMD train step
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SPMDTrainer:
    """Builds and owns the jitted multi-parallel train step.

    mesh_shape: (dp, pp, tp). num_microbatches defaults to pp (minimum for
    a full pipeline)."""

    cfg: T.TransformerConfig
    mesh_shape: Tuple[int, int, int] = (1, 1, 1)
    num_microbatches: Optional[int] = None
    learning_rate: float = 1e-3
    adam_b1: float = 0.9
    adam_b2: float = 0.98
    devices: Any = None

    def __post_init__(self):
        dp, pp, tp = self.mesh_shape
        devs = self.devices if self.devices is not None else jax.devices()
        n = dp * pp * tp
        if len(devs) < n:
            raise ValueError("need %d devices, have %d" % (n, len(devs)))
        self.mesh = Mesh(np.array(devs[:n]).reshape(dp, pp, tp),
                         ("dp", "pp", "tp"))
        self.M = self.num_microbatches or max(pp, 1)
        if self.cfg.n_layers % pp:
            raise ValueError("pp (%d) must divide n_layers (%d)" % (pp, self.cfg.n_layers))
        if self.cfg.n_heads % tp or self.cfg.d_ff % tp:
            raise ValueError("tp (%d) must divide n_heads (%d) and d_ff (%d)" % (tp, self.cfg.n_heads, self.cfg.d_ff))
        if self.cfg.max_seq_len % tp:
            raise ValueError("tp (%d) must divide max_seq_len (%d) for sequence parallelism" % (tp, self.cfg.max_seq_len))
        if self.cfg.n_experts and self.cfg.n_experts % dp:
            raise ValueError("dp (%d) must divide n_experts (%d) for expert parallelism" % (dp, self.cfg.n_experts))
        self.layers_per_stage = self.cfg.n_layers // pp
        self._specs = param_specs(self.cfg)
        self._build()

    # -- construction -------------------------------------------------------
    def _build(self):
        cfg = self.cfg
        dp, pp, tp = self.mesh_shape
        mesh = self.mesh
        M = self.M
        S = self.layers_per_stage

        pspecs = self._specs
        data_spec = P("dp", None)

        def local_loss(params, tokens, labels):
            """Rank-local loss for pp == 1 (no pipeline): embed -> stage ->
            head on the sequence shard; Σ over all ranks == global mean CE."""
            my_tp = _axis_index("tp")
            B_local, T_full = tokens.shape
            t_shard = T_full // tp
            moe_p = params.get("moe")

            h = T.embed_tokens(params, tokens, cfg)
            h = jax.lax.dynamic_slice_in_dim(
                h, my_tp * t_shard, t_shard, axis=1)
            h = _stage_fn(params["layers"], moe_p, h, cfg, S)
            h = T.layer_norm(h, params["final_ln_scale"],
                             params["final_ln_bias"])
            logits = T.lm_logits(params, h, cfg)  # [B, t_shard, V] fp32
            labs = jax.lax.dynamic_slice_in_dim(
                labels, my_tp * t_shard, t_shard, axis=1)
            logp = jax.nn.log_softmax(logits, axis=-1)
            picked = jnp.take_along_axis(logp, labs[..., None], axis=-1)
            total_tokens = B_local * T_full * dp
            return -jnp.sum(picked) / total_tokens

        def pipeline_grads(params, tokens, labels):
            """1F1B pipeline (pp > 1): ONE scan where every tick runs one
            forward microbatch unit and one backward microbatch unit.

            Stage r forwards microbatch i at tick r+i and backwards it at
            tick 2pp-2-r+i; the last stage turns around immediately (its
            bwd of i lands the same tick as its fwd), so backward drains
            while forward fills — the activation stash is a ring buffer of
            stage INPUTS bounded by 2pp microbatches, O(pp) not O(M)
            (GPipe's whole-schedule stash). Backward ticks recompute the
            stage forward under jax.vjp from the stashed input
            (remat-style, the usual 1F1B+recompute cost model).

            Embedding runs ONLY on stage 0 and the vocab head ONLY on the
            last stage — both under lax.cond, whose branches are
            collective-free and therefore skip at run time on the other
            ranks (the round-2 review flagged the masked-GPipe version for
            burning head FLOPs on every stage). Stage compute + its vjp
            contain tp/dp collectives and run unconditionally in lockstep;
            invalid warmup/cooldown ticks process garbage activations whose
            contributions are masked out of the gradient accumulators.

            Returns (rank-local loss contribution, fp32 grads congruent
            with params)."""
            my_pp = _axis_index("pp")
            my_tp = _axis_index("tp")
            B_local, T_full = tokens.shape
            t_shard = T_full // tp
            mb = B_local // M
            has_moe = bool(cfg.n_experts)
            moe_p = params.get("moe") if has_moe else {}
            lp_local = params["layers"]
            total_tokens = B_local * T_full * dp
            tied = cfg.tie_embeddings

            microtoks = tokens.reshape(M, mb, T_full)
            microlabs = labels.reshape(M, mb, T_full)

            head_keys = ["final_ln_scale", "final_ln_bias"] + (
                ["embed"] if tied else ["lm_head"])
            head_p0 = {k: params[k] for k in head_keys}
            emb_p0 = {"embed": params["embed"],
                      "pos_embed": params["pos_embed"]}

            def embed_fn(e_p, toks):
                h = T.embed_tokens({**params, **e_p}, toks, cfg)
                return jax.lax.dynamic_slice_in_dim(
                    h, my_tp * t_shard, t_shard, axis=1)

            def stage_fwd(lp, mp, h_in):
                return _stage_fn(lp, mp if has_moe else None, h_in, cfg, S)

            def head_loss(h_p, h_out, labs_t):
                h = T.layer_norm(h_out, h_p["final_ln_scale"],
                                 h_p["final_ln_bias"])
                logits = T.lm_logits({**params, **h_p}, h, cfg)
                labs = jax.lax.dynamic_slice_in_dim(
                    labs_t, my_tp * t_shard, t_shard, axis=1)
                logp = jax.nn.log_softmax(logits, axis=-1)
                picked = jnp.take_along_axis(logp, labs[..., None], axis=-1)
                return -jnp.sum(picked) / total_tokens

            S_ring = 2 * pp
            zeros_act = jnp.zeros((mb, t_shard, cfg.d_model), cfg.dtype)
            K = M + 2 * pp - 2
            f32z = lambda tree: jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), tree)

            def acc(g_tree, d_tree, valid):
                return jax.tree.map(
                    lambda g, d: g + jnp.where(valid, d, 0).astype(
                        jnp.float32), g_tree, d_tree)

            def tick(carry, t):
                (fwd_recv, bwd_recv, stash,
                 gL, gM, gE, gH, loss_acc) = carry

                # ---- forward unit: microbatch i_f = t - r ----
                i_f = t - my_pp
                valid_f = (i_f >= 0) & (i_f < M)
                i_fc = jnp.clip(i_f, 0, M - 1)
                toks_f = jax.lax.dynamic_index_in_dim(
                    microtoks, i_fc, axis=0, keepdims=False)
                h_in = jax.lax.cond(
                    my_pp == 0,
                    lambda _: embed_fn(emb_p0, toks_f),
                    lambda _: fwd_recv, None)
                h_out = stage_fwd(lp_local, moe_p, h_in)
                stash2 = jax.lax.dynamic_update_index_in_dim(
                    stash, h_in, jnp.mod(i_fc, S_ring), axis=0)
                stash = jnp.where(valid_f, stash2, stash)

                # ---- backward unit: microbatch i_b = t - (2pp-2-r) ----
                i_b = t - (2 * pp - 2 - my_pp)
                valid_b = (i_b >= 0) & (i_b < M)
                i_bc = jnp.clip(i_b, 0, M - 1)
                labs_b = jax.lax.dynamic_index_in_dim(
                    microlabs, i_bc, axis=0, keepdims=False)
                toks_b = jax.lax.dynamic_index_in_dim(
                    microtoks, i_bc, axis=0, keepdims=False)

                # last stage: fwd of i_b happened THIS tick (t = pp-1+i_b),
                # so the head differentiates the h_out just computed
                def head_branch(_):
                    loss_i, hvjp = jax.vjp(
                        lambda hp, h: head_loss(hp, h, labs_b),
                        head_p0, h_out)
                    gh_i, g_out = hvjp(jnp.float32(1.0))
                    return loss_i, gh_i, g_out

                def relay_branch(_):
                    return (jnp.float32(0.0),
                            jax.tree.map(jnp.zeros_like, head_p0),
                            bwd_recv)

                loss_i, gh_i, g_out = jax.lax.cond(
                    my_pp == pp - 1, head_branch, relay_branch, None)

                h_in_b = jax.lax.dynamic_index_in_dim(
                    stash, jnp.mod(i_bc, S_ring), axis=0, keepdims=False)
                _, svjp = jax.vjp(stage_fwd, lp_local, moe_p, h_in_b)
                gl_i, gm_i, g_in = svjp(g_out)

                def emb_branch(_):
                    _, evjp = jax.vjp(
                        lambda ep: embed_fn(ep, toks_b), emb_p0)
                    (ge_i,) = evjp(g_in)
                    return ge_i

                ge_i = jax.lax.cond(
                    my_pp == 0, emb_branch,
                    lambda _: jax.tree.map(jnp.zeros_like, emb_p0), None)

                gL = acc(gL, gl_i, valid_b)
                gM = acc(gM, gm_i, valid_b)
                gE = acc(gE, ge_i, valid_b)
                gH = acc(gH, gh_i, valid_b)
                loss_acc = loss_acc + jnp.where(valid_b, loss_i, 0.0)

                # ---- ring exchanges (unconditional, all ranks) ----
                fwd_next = jax.lax.ppermute(
                    h_out, "pp", [(i, (i + 1) % pp) for i in range(pp)])
                bwd_next = jax.lax.ppermute(
                    g_in, "pp", [(i, (i - 1) % pp) for i in range(pp)])
                return (fwd_next, bwd_next, stash,
                        gL, gM, gE, gH, loss_acc), None

            init = (zeros_act, zeros_act,
                    jnp.zeros((S_ring, mb, t_shard, cfg.d_model), cfg.dtype),
                    f32z(lp_local), f32z(moe_p), f32z(emb_p0),
                    f32z(head_p0), jnp.float32(0.0))
            (_, _, _, gL, gM, gE, gH, loss_acc), _ = jax.lax.scan(
                tick, init, jnp.arange(K))

            grads = {
                "embed": gE["embed"] + (gH["embed"] if tied else 0.0),
                "pos_embed": gE["pos_embed"],
                "final_ln_scale": gH["final_ln_scale"],
                "final_ln_bias": gH["final_ln_bias"],
                "layers": gL,
            }
            if not tied:
                grads["lm_head"] = gH["lm_head"]
            if has_moe:
                grads["moe"] = gM
            return loss_acc, grads

        lr = self.learning_rate
        b1, b2 = self.adam_b1, self.adam_b2

        flat_specs = jax.tree.leaves(
            pspecs, is_leaf=lambda x: isinstance(x, P))

        def spmd_step(params, m_state, v_state, step, tokens, labels):
            if pp == 1:
                contrib, grads = jax.value_and_grad(local_loss)(
                    params, tokens, labels)
            else:
                contrib, grads = pipeline_grads(params, tokens, labels)
            # per-leaf psum over the axes each leaf is replicated on
            flat_g, gdef = jax.tree.flatten(grads)
            flat_g = [
                jax.lax.psum(g, _replicated_axes(s))
                if _replicated_axes(s) else g
                for g, s in zip(flat_g, flat_specs)
            ]
            grads = jax.tree.unflatten(gdef, flat_g)
            # contrib already carries the full 1/total_tokens scaling
            loss = jax.lax.psum(contrib, ("dp", "pp", "tp"))
            # Adam (fp32 state, local shards)
            stepf = (step + 1).astype(jnp.float32)
            bc1 = 1.0 - b1 ** stepf
            bc2 = 1.0 - b2 ** stepf

            def upd(p, g, m, v):
                gf = g.astype(jnp.float32)
                m2 = b1 * m + (1 - b1) * gf
                v2 = b2 * v + (1 - b2) * gf * gf
                p2 = p - lr * (m2 / bc1) / (jnp.sqrt(v2 / bc2) + 1e-8)
                return p2.astype(p.dtype), m2, v2

            flat_p, treedef = jax.tree.flatten(params)
            flat_g = jax.tree.leaves(grads)
            flat_m = jax.tree.leaves(m_state)
            flat_v = jax.tree.leaves(v_state)
            out_p, out_m, out_v = [], [], []
            for pleaf, gleaf, mleaf, vleaf in zip(flat_p, flat_g, flat_m,
                                                  flat_v):
                p2, m2, v2 = upd(pleaf, gleaf, mleaf, vleaf)
                out_p.append(p2)
                out_m.append(m2)
                out_v.append(v2)
            return (jax.tree.unflatten(treedef, out_p),
                    jax.tree.unflatten(treedef, out_m),
                    jax.tree.unflatten(treedef, out_v),
                    step + 1, loss)

        in_specs = (pspecs, pspecs, pspecs, P(), data_spec, data_spec)
        out_specs = (pspecs, pspecs, pspecs, P(), P())
        mapped = shard_map(spmd_step, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, check_vma=False)
        self._step = jax.jit(mapped, donate_argnums=(0, 1, 2))
        self._loss_fn = local_loss

    # -- API ----------------------------------------------------------------
    def init(self, seed=0):
        cfg = self.cfg
        params = T.init_params(jax.random.PRNGKey(seed), cfg)
        shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), self._specs,
            is_leaf=lambda x: isinstance(x, P))
        params = jax.device_put(params, shardings)
        m = jax.device_put(
            jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
            shardings)
        v = jax.device_put(
            jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
            shardings)
        step = jnp.zeros((), jnp.int32)
        return params, m, v, step

    def step(self, state, tokens, labels):
        params, m, v, step = state
        params, m, v, step, loss = self._step(params, m, v, step, tokens,
                                              labels)
        return (params, m, v, step), loss


class _noop:
    def __enter__(self):
        return None

    def __exit__(self, *a):
        return False
