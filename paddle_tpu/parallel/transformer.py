"""SPMD Transformer trainer: dp + pp + tp + sp + ep over one shard_map.

This is the TPU-native replacement for everything the reference built with
ParallelExecutor/NCCL/transpilers (SURVEY §2.3) *plus* the parallel modes
the 2019 reference lacked (tensor/pipeline/sequence/expert parallelism are
new design, per SURVEY §5.7).

Mesh: ("dp", "pp", "tp").
- dp  — data parallel: batch sharded; per-leaf gradient psum over replicated
        axes replaces AllReduceOpHandle (details/all_reduce_op_handle.cc:91).
- pp  — pipeline parallel: layers sharded on their leading [L] axis; GPipe
        microbatch schedule as a lax.scan whose carry rotates activations
        through the stage ring with ppermute (ICI neighbor exchange).
- tp  — tensor parallel (Megatron-style): attention heads + FFN hidden
        sharded; partial outputs reduce via reduce_scatter.
- sp  — sequence parallel on the SAME tp axis: the residual stream between
        blocks is sequence-sharded [B, T/tp, D]; all_gather before each
        matmul, reduce_scatter after — LN/dropout/residual math never
        duplicates across tp.
- ep  — expert parallel on the dp axis: MoE FFN tokens exchanged with
        all_to_all, one expert group per dp rank.

Gradients: jax.grad of the rank-local masked loss inside shard_map; the
collective transposes (all_gather ↔ reduce_scatter, ppermute ↔ reverse
ppermute, all_to_all ↔ all_to_all) route cross-rank cotangents, so the
result is the gradient of the GLOBAL loss wrt local shards. Each leaf is
then psummed over exactly the mesh axes it is replicated on (the axes
absent from its PartitionSpec) — the sharding-aware generalization of the
reference's single gradient allreduce.
"""

import dataclasses
import functools
import math
from typing import Any, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from ..models import transformer as T


# ---------------------------------------------------------------------------
# sharding specs
# ---------------------------------------------------------------------------


def param_specs(cfg: T.TransformerConfig):
    """PartitionSpec pytree congruent with init_params output."""
    specs = {
        "embed": P(None, None),
        "pos_embed": P(None, None),
        "final_ln_scale": P(None),
        "final_ln_bias": P(None),
        "layers": {
            "ln1_scale": P("pp", None),
            "ln1_bias": P("pp", None),
            "wqkv": P("pp", None, None, "tp", None),
            "wo": P("pp", "tp", None, None),
            "ln2_scale": P("pp", None),
            "ln2_bias": P("pp", None),
            "w1": P("pp", None, "tp"),
            "b1": P("pp", "tp"),
            "w2": P("pp", "tp", None),
            "b2": P("pp", None),
        },
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, None)
    if cfg.n_experts:
        specs["moe"] = {
            "router": P(None, None),
            "w1": P("dp", None, None),
            "w2": P("dp", None, None),
        }
    return specs


def _replicated_axes(spec, mesh_axes=("dp", "pp", "tp")):
    used = set(a for a in spec if a is not None)
    return tuple(a for a in mesh_axes if a not in used)


# ---------------------------------------------------------------------------
# rank-local building blocks (run inside shard_map)
# ---------------------------------------------------------------------------


def _block_sp(lp, h_s, cfg):
    """One transformer block on a sequence-sharded residual stream h_s
    [B, T/tp, D]. all_gather('tp') before matmuls, reduce_scatter after —
    Megatron-SP seams."""
    dtype = cfg.dtype

    x = T.layer_norm(h_s, lp["ln1_scale"], lp["ln1_bias"])
    x_full = jax.lax.all_gather(x, "tp", axis=1, tiled=True)  # [B, T, D]
    attn_partial = T.attention_block(lp, x_full, dtype)
    attn_s = jax.lax.psum_scatter(attn_partial, "tp", scatter_dimension=1,
                                  tiled=True)
    h_s = h_s + attn_s

    x = T.layer_norm(h_s, lp["ln2_scale"], lp["ln2_bias"])
    x_full = jax.lax.all_gather(x, "tp", axis=1, tiled=True)
    ffn_partial = T.ffn_block(lp, x_full, dtype)
    ffn_s = jax.lax.psum_scatter(ffn_partial, "tp", scatter_dimension=1,
                                 tiled=True)
    # b2 is tp-replicated; add once on the scattered output
    h_s = h_s + ffn_s + lp["b2"].astype(dtype)
    return h_s


def _moe_block(mp, h_s, cfg):
    """Top-1 switch MoE on the local token shard; experts sharded over the
    dp axis (expert parallelism). h_s: [B, t, D] -> same."""
    dtype = cfg.dtype
    E = cfg.n_experts
    ep = jax.lax.psum(1, "dp")  # ep group size
    e_local = E // ep
    B, t, D = h_s.shape
    N = B * t
    x = h_s.reshape(N, D)

    gates = jax.nn.softmax(
        jnp.einsum("nd,de->ne", x.astype(jnp.float32),
                   mp["router"].astype(jnp.float32)))
    expert = jnp.argmax(gates, axis=-1)  # [N]
    gate = jnp.take_along_axis(gates, expert[:, None], axis=-1)[:, 0]

    cap = int(cfg.expert_capacity_factor * N / E) + 1
    # position of each token within its expert's capacity
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.int32)  # [N, E]
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1  # [N, E], -1 elsewhere
    pos1 = pos.max(axis=-1)  # [N]
    keep = pos1 < cap
    # dispatch [E, cap, D]
    disp = jnp.zeros((E, cap, D), dtype)
    idx_e = jnp.where(keep, expert, 0)
    idx_c = jnp.where(keep, pos1, 0)
    disp = disp.at[idx_e, idx_c].add(
        jnp.where(keep[:, None], x, 0).astype(dtype))
    # all_to_all over dp ("transpose"): send expert-group r's slice to rank
    # r; axis 0 of the result indexes the SOURCE rank.
    disp = disp.reshape(ep, e_local, cap, D)
    recv = jax.lax.all_to_all(disp, "dp", split_axis=0, concat_axis=0)
    toks = recv.transpose(1, 0, 2, 3).reshape(e_local, ep * cap, D)
    # expert FFN (local experts)
    a = jnp.einsum("ecd,edf->ecf", toks, mp["w1"].astype(dtype))
    a = jax.nn.gelu(a)
    out = jnp.einsum("ecf,efd->ecd", a, mp["w2"].astype(dtype))
    # route back: inverse all_to_all
    out = out.reshape(e_local, ep, cap, D).transpose(1, 0, 2, 3)
    back = jax.lax.all_to_all(out, "dp", split_axis=0, concat_axis=0)
    back = back.reshape(E, cap, D)
    # combine
    y = back[idx_e, idx_c]  # [N, D]
    y = jnp.where(keep[:, None], y, 0).astype(jnp.float32)
    y = y * gate[:, None]
    return h_s + y.reshape(B, t, D).astype(dtype)


def _stage_fn(stage_params, moe_params, h_s, cfg, layers_per_stage):
    """Run this pp rank's slice of layers (+ optional MoE) on a
    seq-sharded activation."""
    body = functools.partial(_block_sp, cfg=cfg)
    if cfg.remat:
        body = jax.checkpoint(body)
    for i in range(layers_per_stage):
        lp = jax.tree.map(lambda x: x[i], stage_params)
        h_s = body(lp, h_s)
    if moe_params is not None:
        mb = functools.partial(_moe_block, cfg=cfg)
        if cfg.remat:
            mb = jax.checkpoint(mb)
        h_s = mb(moe_params, h_s)
    return h_s


# ---------------------------------------------------------------------------
# the SPMD train step
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SPMDTrainer:
    """Builds and owns the jitted multi-parallel train step.

    mesh_shape: (dp, pp, tp). num_microbatches defaults to pp (minimum for
    a full pipeline)."""

    cfg: T.TransformerConfig
    mesh_shape: Tuple[int, int, int] = (1, 1, 1)
    num_microbatches: Optional[int] = None
    learning_rate: float = 1e-3
    adam_b1: float = 0.9
    adam_b2: float = 0.98
    devices: Any = None

    def __post_init__(self):
        dp, pp, tp = self.mesh_shape
        devs = self.devices if self.devices is not None else jax.devices()
        n = dp * pp * tp
        if len(devs) < n:
            raise ValueError("need %d devices, have %d" % (n, len(devs)))
        self.mesh = Mesh(np.array(devs[:n]).reshape(dp, pp, tp),
                         ("dp", "pp", "tp"))
        self.M = self.num_microbatches or max(pp, 1)
        if self.cfg.n_layers % pp:
            raise ValueError("pp (%d) must divide n_layers (%d)" % (pp, self.cfg.n_layers))
        if self.cfg.n_heads % tp or self.cfg.d_ff % tp:
            raise ValueError("tp (%d) must divide n_heads (%d) and d_ff (%d)" % (tp, self.cfg.n_heads, self.cfg.d_ff))
        if self.cfg.max_seq_len % tp:
            raise ValueError("tp (%d) must divide max_seq_len (%d) for sequence parallelism" % (tp, self.cfg.max_seq_len))
        if self.cfg.n_experts and self.cfg.n_experts % dp:
            raise ValueError("dp (%d) must divide n_experts (%d) for expert parallelism" % (dp, self.cfg.n_experts))
        self.layers_per_stage = self.cfg.n_layers // pp
        self._specs = param_specs(self.cfg)
        self._build()

    # -- construction -------------------------------------------------------
    def _build(self):
        cfg = self.cfg
        dp, pp, tp = self.mesh_shape
        mesh = self.mesh
        M = self.M
        S = self.layers_per_stage

        pspecs = self._specs
        data_spec = P("dp", None)

        def local_loss(params, tokens, labels):
            """Rank-local masked loss; Σ over all ranks == global mean CE."""
            my_pp = jax.lax.axis_index("pp")
            my_tp = jax.lax.axis_index("tp")
            B_local, T_full = tokens.shape
            t_shard = T_full // tp
            mb = B_local // M
            moe_p = params.get("moe")

            def embed_shard(toks):
                h = T.embed_tokens(params, toks, cfg)  # [mb, T, D]
                return jax.lax.dynamic_slice_in_dim(
                    h, my_tp * t_shard, t_shard, axis=1)

            stage = functools.partial(_stage_fn, cfg=cfg, layers_per_stage=S)

            if pp == 1:
                h = embed_shard(tokens)
                h = stage(params["layers"], moe_p, h)
                outputs = h[None]  # [1, B, t, D]
                out_tokens = tokens[None]
                out_labels = labels[None]
            else:
                microtoks = tokens.reshape(M, mb, T_full)
                microlabs = labels.reshape(M, mb, T_full)

                def tick(carry, t):
                    recv, outputs = carry
                    mb_idx = jnp.clip(t, 0, M - 1)
                    toks_t = jax.lax.dynamic_index_in_dim(
                        microtoks, mb_idx, axis=0, keepdims=False)
                    h0 = embed_shard(toks_t)
                    h_in = jnp.where(my_pp == 0, h0, recv)
                    h_out = stage(params["layers"], moe_p, h_in)
                    out_idx = jnp.clip(t - (pp - 1), 0, M - 1)
                    updated = jax.lax.dynamic_update_index_in_dim(
                        outputs, h_out, out_idx, axis=0)
                    outputs = jnp.where(t >= pp - 1, updated, outputs)
                    recv_next = jax.lax.ppermute(
                        h_out, "pp", [(i, (i + 1) % pp) for i in range(pp)])
                    return (recv_next, outputs), None

                t_shard_shape = (M, mb, t_shard, cfg.d_model)
                init = (jnp.zeros(t_shard_shape[1:], cfg.dtype),
                        jnp.zeros(t_shard_shape, cfg.dtype))
                (_, outputs), _ = jax.lax.scan(
                    tick, init, jnp.arange(M + pp - 1))
                out_tokens = microtoks
                out_labels = microlabs

            # loss on the last pipeline stage, over the local seq shard
            h = outputs  # [M, mb, t_shard, D]
            h = T.layer_norm(h, params["final_ln_scale"],
                             params["final_ln_bias"])
            logits = T.lm_logits(params, h, cfg)  # [M, mb, t_shard, V] fp32
            labs = jax.lax.dynamic_slice_in_dim(
                out_labels, my_tp * t_shard, t_shard, axis=2)
            logp = jax.nn.log_softmax(logits, axis=-1)
            picked = jnp.take_along_axis(logp, labs[..., None], axis=-1)
            total_tokens = B_local * T_full * dp
            contrib = -jnp.sum(picked) / total_tokens
            contrib = jnp.where(my_pp == pp - 1, contrib, 0.0)
            return contrib

        lr = self.learning_rate
        b1, b2 = self.adam_b1, self.adam_b2

        flat_specs = jax.tree.leaves(
            pspecs, is_leaf=lambda x: isinstance(x, P))

        def spmd_step(params, m_state, v_state, step, tokens, labels):
            contrib, grads = jax.value_and_grad(local_loss)(
                params, tokens, labels)
            # per-leaf psum over the axes each leaf is replicated on
            flat_g, gdef = jax.tree.flatten(grads)
            flat_g = [
                jax.lax.psum(g, _replicated_axes(s))
                if _replicated_axes(s) else g
                for g, s in zip(flat_g, flat_specs)
            ]
            grads = jax.tree.unflatten(gdef, flat_g)
            # contrib already carries the full 1/total_tokens scaling
            loss = jax.lax.psum(contrib, ("dp", "pp", "tp"))
            # Adam (fp32 state, local shards)
            stepf = (step + 1).astype(jnp.float32)
            bc1 = 1.0 - b1 ** stepf
            bc2 = 1.0 - b2 ** stepf

            def upd(p, g, m, v):
                gf = g.astype(jnp.float32)
                m2 = b1 * m + (1 - b1) * gf
                v2 = b2 * v + (1 - b2) * gf * gf
                p2 = p - lr * (m2 / bc1) / (jnp.sqrt(v2 / bc2) + 1e-8)
                return p2.astype(p.dtype), m2, v2

            flat_p, treedef = jax.tree.flatten(params)
            flat_g = jax.tree.leaves(grads)
            flat_m = jax.tree.leaves(m_state)
            flat_v = jax.tree.leaves(v_state)
            out_p, out_m, out_v = [], [], []
            for pleaf, gleaf, mleaf, vleaf in zip(flat_p, flat_g, flat_m,
                                                  flat_v):
                p2, m2, v2 = upd(pleaf, gleaf, mleaf, vleaf)
                out_p.append(p2)
                out_m.append(m2)
                out_v.append(v2)
            return (jax.tree.unflatten(treedef, out_p),
                    jax.tree.unflatten(treedef, out_m),
                    jax.tree.unflatten(treedef, out_v),
                    step + 1, loss)

        in_specs = (pspecs, pspecs, pspecs, P(), data_spec, data_spec)
        out_specs = (pspecs, pspecs, pspecs, P(), P())
        mapped = shard_map(spmd_step, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, check_vma=False)
        self._step = jax.jit(mapped, donate_argnums=(0, 1, 2))
        self._loss_fn = local_loss

    # -- API ----------------------------------------------------------------
    def init(self, seed=0):
        cfg = self.cfg
        params = T.init_params(jax.random.PRNGKey(seed), cfg)
        shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), self._specs,
            is_leaf=lambda x: isinstance(x, P))
        params = jax.device_put(params, shardings)
        m = jax.device_put(
            jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
            shardings)
        v = jax.device_put(
            jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
            shardings)
        step = jnp.zeros((), jnp.int32)
        return params, m, v, step

    def step(self, state, tokens, labels):
        params, m, v, step = state
        params, m, v, step, loss = self._step(params, m, v, step, tokens,
                                              labels)
        return (params, m, v, step), loss


class _noop:
    def __enter__(self):
        return None

    def __exit__(self, *a):
        return False
