"""Static pipeline schedules as host-precomputed tables (round-4 VERDICT
weak #4: bubble accounting + interleaved virtual stages).

The 1F1B schedule is data-independent, so it is built ONCE on the host by
a greedy list scheduler and handed to the traced step as int32 tables
indexed [tick, pp_rank] — the scan body just looks its row up. That
single mechanism covers classic 1F1B (v=1, reproducing the closed-form
K = M + 2*pp - 2 tick count) and Megatron-style interleaved virtual
stages (v>1: each rank hosts v non-contiguous chunks; virtual stage s
lives on rank s % pp, so consecutive stages sit on consecutive ranks and
the SAME +1/-1 ppermute ring carries both schedules). Because a chunk is
1/v of the layers, a tick costs ~1/v of a v=1 tick: the bubble fraction
drops from (2pp-2)/(M+2pp-2) toward its 1/v multiple (the measured table
lives in docs/PARALLEL.md).

Every generated schedule is validated against the dependency rules
(wire arrival = send tick + 1) at build time — an invalid schedule is a
bug and raises, it cannot silently corrupt gradients.

Unit semantics per tick per rank (mirrors PipelineProgramStep's scan
body): at most ONE forward chunk-unit and ONE backward chunk-unit; the
backward of (s, i) may run in the SAME tick as its forward (the stash
write happens earlier in the tick body); ring wires sent at tick t are
readable from tick t+1.
"""

import numpy as np

__all__ = ["Schedule", "build_schedule"]


class Schedule:
    """Precomputed tables, all int32 [K, pp]; -1 = no-op / no slot.

    fwd_mb / fwd_chunk   microbatch + chunk of the tick's forward unit
    fwd_read             arrive-stash slot holding its input wire
                         (-1: virtual stage 0, reads the feed)
    fwd_save             input-stash slot to save the input for backward
    fwd_recv             arrive-stash slot for the wire arriving this
                         tick on the forward ring
    bwd_mb / bwd_chunk   backward unit (vjp of the chunk forward)
    bwd_read             input-stash slot with the saved forward input
    cot_read             cot-stash slot with the arrived cotangent
                         (-1 when this unit seeds at the loss stage or
                         runs a post-loss stage with zero cotangent)
    cot_recv             cot-stash slot for the wire arriving this tick
                         on the backward ring
    """

    def __init__(self, pp, v, M, tables, arrive_slots, input_slots,
                 cot_slots):
        self.pp, self.v, self.M = pp, v, M
        self.S = v * pp
        self.K = tables["fwd_mb"].shape[0]
        for k, t in tables.items():
            setattr(self, k, np.asarray(t, np.int32))
        self.arrive_slots = max(arrive_slots, 1)
        self.input_slots = max(input_slots, 1)
        self.cot_slots = max(cot_slots, 1)

    # -- efficiency accounting (docs/PARALLEL.md) ------------------------
    def stats(self):
        """Bubble accounting. A tick costs one chunk fwd + one chunk bwd
        on every rank whether units are valid or not (masked compute
        still runs), so cost-per-tick ~ 1/v of a v=1 tick and the ideal
        schedule would need M*v ticks; bubble = 1 - M*v/K."""
        valid_f = int((self.fwd_mb >= 0).sum())
        valid_b = int((self.bwd_mb >= 0).sum())
        ideal_ticks = self.M * self.v  # per rank: M microbatches x v chunks
        return {
            "pp": self.pp, "virtual_stages": self.v,
            "microbatches": self.M, "ticks": self.K,
            "ideal_ticks": ideal_ticks,
            "bubble_fraction": 1.0 - ideal_ticks / float(self.K),
            "equivalent_full_ticks": self.K / float(self.v),
            "unit_utilization": (valid_f + valid_b)
            / float(2 * self.K * self.pp),
        }


def build_schedule(pp, M, v=1):
    """Greedy list scheduler for (interleaved) 1F1B.

    Virtual stage s in [0, S), S = v*pp, lives on rank s % pp (chunk
    c = s // pp). Readiness rules:
      fwd(s, i): s == 0, or fwd(s-1, i) finished at a tick < t
      bwd(s, i): fwd(s, i) finished at a tick <= t, and
                 (s == S-1, or bwd(s+1, i) finished at a tick < t)
    Per tick each rank runs at most one fwd and one bwd unit. Priorities
    (which make v=1 reproduce classic 1F1B exactly and v>1 come out
    Megatron-interleaved): backward prefers the OLDEST ready microbatch
    at the DEEPEST stage; forward prefers the deepest ready stage, then
    the oldest microbatch — "drain before fill" keeps the in-flight
    window (and the stash sizes) at the 1F1B bound."""
    if pp < 1 or v < 1 or M < 1:
        raise ValueError("pp, v, M must be >= 1")
    S = v * pp
    fwd_done = {}   # (s, i) -> tick
    bwd_done = {}
    # slot managers: per rank free-lists, max watermark = array size
    arrive_owner = {}  # (s, i) -> slot   (fwd wire awaiting consumption)
    input_owner = {}   # (s, i) -> slot   (saved fwd input awaiting bwd)
    cot_owner = {}     # (s, i) -> slot   (cotangent awaiting bwd)
    free = {"arr": [set() for _ in range(pp)],
            "inp": [set() for _ in range(pp)],
            "cot": [set() for _ in range(pp)]}
    high = {"arr": [0] * pp, "inp": [0] * pp, "cot": [0] * pp}

    def take(kind, r):
        pool = free[kind][r]
        if pool:
            return pool.pop()
        slot = high[kind][r]
        high[kind][r] += 1
        return slot

    def give(kind, r, slot):
        free[kind][r].add(slot)

    cols = ["fwd_mb", "fwd_chunk", "fwd_read", "fwd_save", "fwd_recv",
            "bwd_mb", "bwd_chunk", "bwd_read", "cot_read", "cot_recv"]
    rows = {k: [] for k in cols}
    # wires in flight: sent at tick t, land at t+1
    fly_fwd = [None] * pp   # per SOURCE rank: (s, i) the wire carries
    fly_cot = [None] * pp

    t = 0
    limit = 4 * (M * S + S * S + 16)  # far above any legit schedule
    while len(bwd_done) < S * M:
        if t > limit:
            raise AssertionError(
                "pipeline scheduler failed to converge (pp=%d v=%d M=%d)"
                % (pp, v, M))
        row = {k: [-1] * pp for k in cols}

        # -- land last tick's wires ----------------------------------
        landed_fwd = [None] * pp
        landed_cot = [None] * pp
        for src in range(pp):
            if fly_fwd[src] is not None:
                s, i = fly_fwd[src]
                dst = (src + 1) % pp
                slot = take("arr", dst)
                arrive_owner[(s + 1, i)] = slot
                row["fwd_recv"][dst] = slot
                landed_fwd[dst] = (s + 1, i)
            if fly_cot[src] is not None:
                s, i = fly_cot[src]
                dst = (src - 1) % pp
                slot = take("cot", dst)
                cot_owner[(s - 1, i)] = slot
                row["cot_recv"][dst] = slot
                landed_cot[dst] = (s - 1, i)
        fly_fwd = [None] * pp
        fly_cot = [None] * pp

        for r in range(pp):
            # -- forward unit ---------------------------------------
            cands = []
            for c in range(v):
                s = c * pp + r
                for i in range(M):
                    if (s, i) in fwd_done:
                        continue
                    if s == 0 or fwd_done.get((s - 1, i), t) < t or \
                            landed_fwd[r] == (s, i):
                        # wire that landed THIS tick is readable: the
                        # stash write precedes the fwd unit in the body
                        if s == 0 or (s, i) in arrive_owner:
                            cands.append((s, i))
                    break  # per chunk, microbatches go in order
            fwd_unit = max(cands, key=lambda si: (si[0], -si[1])) \
                if cands else None
            if fwd_unit is not None:
                s, i = fwd_unit
                row["fwd_mb"][r] = i
                row["fwd_chunk"][r] = s // pp
                if s > 0:
                    slot = arrive_owner.pop((s, i))
                    row["fwd_read"][r] = slot
                    give("arr", r, slot)
                slot = take("inp", r)
                input_owner[(s, i)] = slot
                row["fwd_save"][r] = slot
                fwd_done[(s, i)] = t
                if s < S - 1:
                    fly_fwd[r] = (s, i)

            # -- backward unit --------------------------------------
            cands = []
            for c in range(v):
                s = c * pp + r
                for i in range(M):
                    if (s, i) in bwd_done:
                        continue
                    if (s, i) not in fwd_done:  # includes same-tick fwd
                        break
                    if s == S - 1 or bwd_done.get((s + 1, i), t) < t or \
                            landed_cot[r] == (s, i):
                        if s == S - 1 or (s, i) in cot_owner:
                            cands.append((s, i))
                    break
            bwd_unit = max(cands, key=lambda si: (si[0], -si[1])) \
                if cands else None
            if bwd_unit is not None:
                s, i = bwd_unit
                row["bwd_mb"][r] = i
                row["bwd_chunk"][r] = s // pp
                slot = input_owner.pop((s, i))
                row["bwd_read"][r] = slot
                give("inp", r, slot)
                if s < S - 1:
                    slot = cot_owner.pop((s, i))
                    row["cot_read"][r] = slot
                    give("cot", r, slot)
                bwd_done[(s, i)] = t
                if s > 0:
                    fly_cot[r] = (s, i)

        for k in cols:
            rows[k].append(row[k])
        t += 1

    tables = {k: np.array(rows[k], np.int32) for k in cols}
    sched = Schedule(pp, v, M, tables, max(high["arr"]), max(high["inp"]),
                     max(high["cot"]))
    _validate(sched)
    return sched


def _validate(sched):
    """Re-check the emitted tables against the dependency rules by
    simulating ONLY the tables (no scheduler state): every microbatch
    must flow 0..S-1 forward then S-1..0 backward with wire latency 1
    (strict — an upstream forward the SAME tick is a violation), and
    every stash slot read must return exactly what the schedule last
    stored there (a free-list bug would surface here, not as silently
    wrong gradients)."""
    pp, v, M, S = sched.pp, sched.v, sched.M, sched.S
    fwd_at, bwd_at = {}, {}
    # per-rank slot contents: slot index -> the (s, i) unit it serves
    arr = [dict() for _ in range(pp)]   # arrived fwd wire for unit (s,i)
    inp = [dict() for _ in range(pp)]   # saved fwd input/residuals
    cot = [dict() for _ in range(pp)]   # arrived cotangent for (s,i)
    for t in range(sched.K):
        # land wires sent at t-1 (ring: fwd +1, cot -1)
        if t > 0:
            for src in range(pp):
                i = int(sched.fwd_mb[t - 1, src])
                if i >= 0:
                    s = int(sched.fwd_chunk[t - 1, src]) * pp + src
                    if s < S - 1:
                        dst = (src + 1) % pp
                        slot = int(sched.fwd_recv[t, dst])
                        assert slot >= 0, "fwd wire landed with no slot"
                        arr[dst][slot] = (s + 1, i)
                i = int(sched.bwd_mb[t - 1, src])
                if i >= 0:
                    s = int(sched.bwd_chunk[t - 1, src]) * pp + src
                    if s > 0:
                        dst = (src - 1) % pp
                        slot = int(sched.cot_recv[t, dst])
                        assert slot >= 0, "cot wire landed with no slot"
                        cot[dst][slot] = (s - 1, i)
        for r in range(pp):
            i = int(sched.fwd_mb[t, r])
            if i >= 0:
                s = int(sched.fwd_chunk[t, r]) * pp + r
                assert (s, i) not in fwd_at, "fwd unit duplicated"
                if s == 0:
                    assert int(sched.fwd_read[t, r]) < 0, \
                        "stage 0 reads the feed, not a wire slot"
                else:
                    assert fwd_at.get((s - 1, i), t) < t, \
                        "fwd before its producer's wire can arrive"
                    slot = int(sched.fwd_read[t, r])
                    assert arr[r].get(slot) == (s, i), \
                        "fwd read a stale/foreign arrive slot"
                    del arr[r][slot]
                save = int(sched.fwd_save[t, r])
                assert save >= 0 and save not in inp[r], \
                    "fwd save slot missing or still live"
                inp[r][save] = (s, i)
                fwd_at[(s, i)] = t
            i = int(sched.bwd_mb[t, r])
            if i >= 0:
                s = int(sched.bwd_chunk[t, r]) * pp + r
                assert (s, i) not in bwd_at, "bwd unit duplicated"
                assert fwd_at.get((s, i), t + 1) <= t, "bwd before fwd"
                slot = int(sched.bwd_read[t, r])
                assert inp[r].get(slot) == (s, i), \
                    "bwd read a stale/foreign input slot"
                del inp[r][slot]
                if s == S - 1:
                    assert int(sched.cot_read[t, r]) < 0, \
                        "the last stage seeds, it has no cotangent wire"
                else:
                    assert bwd_at.get((s + 1, i), t) < t, \
                        "bwd before its consumer's cotangent can arrive"
                    slot = int(sched.cot_read[t, r])
                    assert cot[r].get(slot) == (s, i), \
                        "bwd read a stale/foreign cot slot"
                    del cot[r][slot]
                bwd_at[(s, i)] = t
    assert len(fwd_at) == S * M and len(bwd_at) == S * M, \
        "schedule incomplete"
    # classic 1F1B tick-count sanity: v=1 must match the closed form
    if v == 1:
        assert sched.K == M + 2 * pp - 2, \
            "v=1 schedule is not 1F1B-optimal: K=%d != %d" % (
                sched.K, M + 2 * pp - 2)
