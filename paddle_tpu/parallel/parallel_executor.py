"""ParallelExecutor facade (parity: framework/parallel_executor.cc:195/:513 +
python ParallelExecutor wrapper).

TPU-native: no per-device graph replication or op-handle scheduling — the
program compiles once as an SPMD computation over the data mesh
(compiler._DataParallelStep); XLA inserts the gradient all-reduces over ICI.
"""

import numpy as np

from .. import framework
from ..compiler import BuildStrategy, CompiledProgram, ExecutionStrategy

__all__ = ["ParallelExecutor"]


class ParallelExecutor:
    def __init__(self, use_cuda=True, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None, build_strategy=None,
                 num_trainers=1, trainer_id=0, scope=None, amp=False):
        self._program = main_program or framework.default_main_program()
        if amp:
            # convenience: flip the BuildStrategy AMP knob so the bf16
            # dtype rewrite (docs/MIXED_PRECISION.md) applies to this
            # executor's compiled step — bf16 gradients also halve the
            # bytes GSPMD's data-parallel all-reduces move over ICI.
            # Copy a caller-supplied strategy: a shared BuildStrategy
            # must not silently go mixed-precision for OTHER executors
            import copy

            build_strategy = copy.copy(build_strategy) \
                if build_strategy is not None else BuildStrategy()
            build_strategy.amp = True
        self._compiled = CompiledProgram(self._program).with_data_parallel(
            loss_name=loss_name,
            build_strategy=build_strategy or BuildStrategy(),
            exec_strategy=exec_strategy or ExecutionStrategy(),
            share_vars_from=share_vars_from and share_vars_from._compiled,
        )
        self._scope = scope
        from ..executor import Executor

        self._exe = Executor()

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        feed = feed if feed is not None else feed_dict
        return self._compiled._run(self._exe, feed, fetch_list, self._scope,
                                   return_numpy)

    @property
    def device_count(self):
        import jax

        return len(jax.devices())

    def drop_local_exe_scopes(self):
        pass
