"""Fault-tolerant streaming data plane (docs/DATA_PLANE.md).

Production input services treat ingestion as a first-class fault domain
(tf.data service, Murray et al. VLDB 2021; CheckFreq, Mohan et al. FAST
2021): one truncated shard, one dead shuffle peer or one restart
mid-epoch must degrade the pipeline, not kill or silently skew the run.
Three cooperating pieces, all metered through `data/*` counters:

  corrupt-input containment — `iter_shard_records` re-implements the
      recordio chunk format (native/recordio.cc layout) with per-chunk
      CRC / per-record framing / truncated-tail detection and routes
      every anomaly through `PTPU_DATA_ANOMALY_POLICY`:
        abort            raise a structured `DataAnomalyError`
        skip_record      skip the damaged records, keep the shard
        quarantine_shard abandon the shard at its first damage point
                         (each pass yields only the stable good
                         prefix; the registry lists the shard for
                         operators — it is telemetry, never iteration
                         state, so resume stays bitwise)
      default `skip_record` — a streaming epoch survives damage by
      default; on HEALTHY shards every policy yields the bitwise-legacy
      record stream (pinned by test), including snappy-compressed
      reference-format chunks (decoded inline, native-scanner parity).
  peer-loss degradation — lives in `distributed_runtime.exchange_samples`
      (per-peer retry budget + deterministic re-partition; see
      docs/DATA_PLANE.md "Degradation contract").
  mid-epoch resume — `DatasetCursor` names an exact position in the
      deterministic record stream (epoch, shard-order seed, shard
      index, in-shard record offset). `Dataset.resumable_batches`
      advances it as batches are CONSUMED (never as they are
      prefetched — queued batches carry their post-batch cursor state
      and apply it only on consumption, which is what makes the
      prefetcher drain state checkpoint-exact), and `write_to(scope)`
      parks it under `__data_cursor__` so it rides the PR-4 checkpoint
      manifest with zero format changes: `ResilientTrainer.restore()`
      brings it back and the resumed record stream is byte-identical
      to the unfailed run.

Deterministic chaos: the `data_corrupt_shard:N` / `data_stall_shard:N` /
`data_peer_die_at_exchange:K` injector sites (resilience.FaultInjector)
make every path above CI-reproducible — scripts/ci.sh `data-chaos`.
"""

import os
import random
import struct
import time
import warnings
import zlib

import numpy as np

from .analysis.concurrency import make_lock
from .flags import env as _env
from .observability import flight_recorder as _blackbox
from .observability import metrics as _metrics
from .recordio_writer import RecordFormatError, deserialize_sample

__all__ = [
    "DATA_POLICY_ABORT", "DATA_POLICY_SKIP_RECORD",
    "DATA_POLICY_QUARANTINE_SHARD", "DATA_POLICIES",
    "data_anomaly_policy", "DataAnomalyError", "iter_shard_records",
    "resilient_sample_reader", "quarantined_shards", "reset_quarantine",
    "DatasetCursor", "shard_order", "apply_cursor",
]


# ---------------------------------------------------------------------------
# anomaly policy
# ---------------------------------------------------------------------------

DATA_POLICY_ABORT = "abort"
DATA_POLICY_SKIP_RECORD = "skip_record"
DATA_POLICY_QUARANTINE_SHARD = "quarantine_shard"
DATA_POLICIES = (DATA_POLICY_ABORT, DATA_POLICY_SKIP_RECORD,
                 DATA_POLICY_QUARANTINE_SHARD)


def data_anomaly_policy(value=None):
    """Resolve the data-plane anomaly policy: explicit arg >
    $PTPU_DATA_ANOMALY_POLICY > `skip_record` (a streaming epoch should
    survive one torn shard by default; docs/DATA_PLANE.md)."""
    policy = value or _env("PTPU_DATA_ANOMALY_POLICY") \
        or DATA_POLICY_SKIP_RECORD
    if policy not in DATA_POLICIES:
        raise ValueError("unknown data anomaly policy %r (want one of %s)"
                         % (policy, "|".join(DATA_POLICIES)))
    return policy


class DataAnomalyError(RuntimeError):
    """Structured corrupt-input failure (policy `abort`): which shard,
    what kind of damage (`crc`, `framing`, `truncated`, `record`,
    `injected`), where."""

    def __init__(self, shard, kind, chunk_index=None, record_index=None,
                 detail=""):
        msg = "corrupt input in shard %r (%s" % (shard, kind)
        if chunk_index is not None:
            msg += ", chunk %d" % chunk_index
        if record_index is not None:
            msg += ", record %d" % record_index
        msg += ")"
        if detail:
            msg += ": " + detail
        super().__init__(msg)
        self.shard = shard
        self.kind = kind
        self.chunk_index = chunk_index
        self.record_index = record_index
        self.detail = detail


# ---------------------------------------------------------------------------
# quarantine registry (process-local shard out-of-service list)
# ---------------------------------------------------------------------------

_quarantine_lock = make_lock("data.quarantine")
_QUARANTINED = set()


def quarantined_shards():
    """Snapshot of shard paths the `quarantine_shard` policy has taken
    out of service — the operator surface for "replace these files".
    The registry is telemetry, NOT iteration state: every pass re-reads
    a damaged shard's stable good prefix and stops at the on-disk
    damage point, so the record stream is a pure function of (bytes on
    disk, policy) and a kill-then-resume run stays bitwise identical
    to the unfailed one (the DatasetCursor contract)."""
    with _quarantine_lock:
        return set(_QUARANTINED)


def reset_quarantine():
    """Clear the quarantine registry (tests / operator override after
    replacing the damaged files)."""
    with _quarantine_lock:
        _QUARANTINED.clear()


def _quarantine(path):
    with _quarantine_lock:
        new = path not in _QUARANTINED
        _QUARANTINED.add(path)
    if new:
        _metrics.counter("data/shards_quarantined").inc()
        _blackbox.record_event("shard_quarantined", shard=str(path))
    return new


# ---------------------------------------------------------------------------
# resilient recordio shard reader
# ---------------------------------------------------------------------------

# native/recordio.cc layout (little-endian):
#   plain chunk   : magic u32 'PTRC', num_records u32, raw u64, crc u32,
#                   raw payload bytes
#   deflate chunk : magic u32 'PTRZ', num_records u32, raw u64,
#                   comp u64, crc u32 (of the RAW payload), zlib stream
#   payload       : (len u32, bytes)* back to back
_MAGIC_PLAIN = 0x50545243
_MAGIC_DEFLATE = 0x5A545243
_MAGIC_REFERENCE = 0x01020304  # reference-format chunks: native scanner
_MAX_CHUNK_BYTES = 1 << 30     # recordio.cc kMaxChunkBytes


_CRC32C_TABLE = None


def _crc32c(buf):
    """CRC-32C (Castagnoli) — the snappy framing format's per-chunk
    checksum (native/recordio.cc crc32c_impl)."""
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        tab = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (0x82F63B78 ^ (c >> 1)) if (c & 1) else (c >> 1)
            tab.append(c)
        _CRC32C_TABLE = tab
    tab = _CRC32C_TABLE
    c = 0xFFFFFFFF
    for b in buf:
        c = tab[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


def _snappy_block_uncompress(src):
    """One raw snappy block (varint uncompressed length, then
    literal/copy elements), ported from native/recordio.cc's from-spec
    decoder. Returns the decoded bytes, or None on any malformed input
    (bounds, bad offsets, length mismatch)."""
    n = len(src)
    pos = 0
    ulen = 0
    shift = 0
    while True:
        if pos >= n or shift > 35:
            return None
        b = src[pos]
        pos += 1
        ulen |= (b & 0x7F) << shift
        if not (b & 0x80):
            break
        shift += 7
    if ulen >= _MAX_CHUNK_BYTES:
        return None
    out = bytearray()
    while pos < n:
        tag = src[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:  # literal
            ln = tag >> 2
            if ln >= 60:
                nb = ln - 59  # 1..4 length bytes
                if pos + nb > n:
                    return None
                ln = int.from_bytes(src[pos:pos + nb], "little")
                pos += nb
            ln += 1
            if pos + ln > n or len(out) + ln > ulen:
                return None
            out += src[pos:pos + ln]
            pos += ln
        else:  # copy
            if kind == 1:
                if pos + 1 > n:
                    return None
                ln = ((tag >> 2) & 0x7) + 4
                offset = ((tag >> 5) << 8) | src[pos]
                pos += 1
            elif kind == 2:
                if pos + 2 > n:
                    return None
                ln = (tag >> 2) + 1
                offset = src[pos] | (src[pos + 1] << 8)
                pos += 2
            else:
                if pos + 4 > n:
                    return None
                ln = (tag >> 2) + 1
                offset = int.from_bytes(src[pos:pos + 4], "little")
                pos += 4
            if offset == 0 or offset > len(out) or len(out) + ln > ulen:
                return None
            frm = len(out) - offset
            for i in range(ln):  # may overlap: byte-wise
                out.append(out[frm + i])
    return bytes(out) if len(out) == ulen else None


def _snappy_framed_uncompress(data):
    """Snappy framing format — (type u8, len u24le, body)* with a
    'sNaPpY' stream id and masked CRC-32C per data chunk —
    native/recordio.cc parity. Returns the decoded bytes, or None on
    malformed input."""
    n = len(data)
    pos = 0
    out = bytearray()
    while pos < n:
        if pos + 4 > n:
            return None
        ftype = data[pos]
        ln = int.from_bytes(data[pos + 1:pos + 4], "little")
        pos += 4
        if pos + ln > n:
            return None
        body = data[pos:pos + ln]
        if ftype == 0xFF:
            if ln != 6 or body != b"sNaPpY":
                return None
        elif ftype in (0x00, 0x01):
            if ln < 4:
                return None
            masked = int.from_bytes(body[:4], "little")
            piece = (_snappy_block_uncompress(body[4:]) if ftype == 0x00
                     else bytes(body[4:]))
            if piece is None:
                return None
            crc = _crc32c(piece)
            want = ((((crc >> 15) | (crc << 17)) & 0xFFFFFFFF)
                    + 0xA282EAD8) & 0xFFFFFFFF
            if want != masked:
                return None
            if len(out) + len(piece) >= _MAX_CHUNK_BYTES:
                return None
            out += piece
        elif 0x02 <= ftype <= 0x7F:
            return None  # reserved unskippable
        # 0x80-0xfd reserved skippable, 0xfe padding: skip
        pos += ln
    return bytes(out)


class _ChunkDamage(Exception):
    """Internal: one chunk failed verification but the stream is
    positioned at the next chunk header (containment can continue)."""

    def __init__(self, kind, num_records, detail):
        super().__init__(detail)
        self.kind = kind
        self.num_records = num_records
        self.detail = detail


class _ShardTorn(Exception):
    """Internal: the shard's tail is unreadable (truncated header or
    payload, implausible declared size) — no further chunk boundary is
    recoverable, so containment must stop the shard here."""

    def __init__(self, detail):
        super().__init__(detail)
        self.detail = detail


def _read_chunk(f, force_corrupt=False):
    """Read and verify one chunk; returns (payload_bytes, num_records)
    or None at clean EOF. Raises _ChunkDamage (recoverable — the file
    is positioned at the next chunk) or _ShardTorn (fatal for this
    shard). `force_corrupt` fails the CRC verdict while still consuming
    the chunk's bytes — the `data_corrupt_shard` injector's hook."""
    head = f.read(4)
    if not head:
        return None
    if len(head) < 4:
        raise _ShardTorn("truncated chunk magic (%d byte tail)"
                         % len(head))
    (magic,) = struct.unpack("<I", head)
    if magic == _MAGIC_PLAIN or magic == _MAGIC_DEFLATE:
        deflate = magic == _MAGIC_DEFLATE
        hdr_len = 20 if not deflate else 28
        hdr = f.read(hdr_len - 4)
        if len(hdr) < hdr_len - 4:
            raise _ShardTorn("truncated chunk header")
        if deflate:
            num, raw_len, comp_len, crc = struct.unpack("<IQQI", hdr)
        else:
            num, raw_len, crc = struct.unpack("<IQI", hdr)
            comp_len = raw_len
        if raw_len >= _MAX_CHUNK_BYTES or comp_len >= _MAX_CHUNK_BYTES:
            raise _ShardTorn("implausible declared chunk size %d"
                             % max(raw_len, comp_len))
        stored = f.read(comp_len)
        if len(stored) < comp_len:
            raise _ShardTorn("truncated chunk payload (%d of %d bytes)"
                             % (len(stored), comp_len))
        if deflate:
            try:
                payload = zlib.decompress(stored)
            except zlib.error as e:
                raise _ChunkDamage("crc", num,
                                   "deflate stream damaged: %s" % e)
            if len(payload) != raw_len:
                raise _ChunkDamage("crc", num,
                                   "decompressed size mismatch")
        else:
            payload = stored
        if force_corrupt or zlib.crc32(payload) != crc:
            raise _ChunkDamage("crc", num, "chunk CRC mismatch"
                               if not force_corrupt
                               else "injected CRC failure "
                                    "(data_corrupt_shard)")
        return payload, num
    if magic == _MAGIC_REFERENCE:
        # reference-written chunk: header tail u32x4 {num, checksum (of
        # the bytes AS STORED), compressor, compress_size}. The
        # resilient reader verifies the stored-bytes CRC (that's the
        # containment) and decodes both reference kinds inline —
        # kNoCompress verbatim, kSnappy through the same from-spec
        # framing decoder the native scanner uses — so healthy
        # reference shards stream bitwise-identically to the legacy
        # `recordio_reader_creator` path under every policy
        hdr = f.read(16)
        if len(hdr) < 16:
            raise _ShardTorn("truncated reference chunk header")
        num, checksum, compressor, csize = struct.unpack("<IIII", hdr)
        if csize >= _MAX_CHUNK_BYTES:
            raise _ShardTorn("implausible reference chunk size %d"
                             % csize)
        stored = f.read(csize)
        if len(stored) < csize:
            raise _ShardTorn("truncated reference chunk payload")
        if force_corrupt or zlib.crc32(stored) != checksum:
            raise _ChunkDamage("crc", num, "reference chunk CRC mismatch"
                               if not force_corrupt
                               else "injected CRC failure "
                                    "(data_corrupt_shard)")
        if compressor == 0:  # kNoCompress
            return stored, num
        if compressor == 1:  # kSnappy (framing format)
            payload = _snappy_framed_uncompress(stored)
            if payload is None:
                raise _ChunkDamage("framing", num,
                                   "snappy framed stream damaged")
            return payload, num
        # kGzip is unimplemented in the reference too — the native
        # scanner rejects it identically (recordio.cc returns -2)
        raise _ChunkDamage("framing", num,
                           "unsupported reference compressor %d"
                           % compressor)
    raise _ShardTorn("bad chunk magic 0x%08x" % magic)


_torn_tail_cache = {}
_torn_tail_lock = make_lock("data_plane.torn_tail_cache")


def _torn_tail(path):
    """After a CLEAN native scan every chunk parsed whole, so the only
    damage the C scanner can have missed is a trailing fragment shorter
    than the 4-byte chunk magic — recordio.cc's `fread(&magic,4,1)!=1`
    reads that as plain EOF (-1), where the Python reader raises
    `_ShardTorn("truncated chunk magic")`. Header-walk the chunk layout
    (seeks only — no payload reads, no CRC) and return
    `(fragment_len, chunk_count)`; (0, n) means a genuinely clean tail.
    Any header inconsistency returns clean — the scan just verified
    these bytes, so disagreeing with it here would be a walk bug.
    Verdicts cache per (size, mtime): a multi-epoch run pays the walk
    once per shard, not once per pass."""
    try:
        st = os.stat(path)
    except OSError:
        return 0, 0
    size = st.st_size
    key = (size, st.st_mtime_ns)
    with _torn_tail_lock:
        hit = _torn_tail_cache.get(path)
        if hit is not None and hit[0] == key:
            return hit[1]
    def walk():
        chunks = 0
        pos = 0
        with open(path, "rb") as f:
            while True:
                rem = size - pos
                if rem == 0:
                    return 0, chunks
                if rem < 4:
                    return rem, chunks
                f.seek(pos)
                head = f.read(min(28, rem))
                (magic,) = struct.unpack_from("<I", head, 0)
                if magic == _MAGIC_PLAIN:
                    if len(head) < 20:
                        return 0, chunks
                    (raw,) = struct.unpack_from("<Q", head, 8)
                    pos += 20 + raw
                elif magic == _MAGIC_DEFLATE:
                    if len(head) < 28:
                        return 0, chunks
                    (comp,) = struct.unpack_from("<Q", head, 16)
                    pos += 28 + comp
                elif magic == _MAGIC_REFERENCE:
                    if len(head) < 20:
                        return 0, chunks
                    (csize,) = struct.unpack_from("<I", head, 16)
                    pos += 20 + csize
                else:
                    return 0, chunks
                if pos > size:
                    return 0, chunks
                chunks += 1

    try:
        verdict = walk()
    except OSError:
        return 0, 0  # raced a delete/replace: no verdict, no cache
    with _torn_tail_lock:
        _torn_tail_cache[path] = (key, verdict)
    return verdict


def _split_records(payload, num_records):
    """Split a verified chunk payload into records. Returns (records,
    damage) where damage is a _ChunkDamage for a framing overrun (the
    already-split prefix is still good)."""
    records = []
    off, size = 0, len(payload)
    while off < size:
        if off + 4 > size:
            return records, _ChunkDamage(
                "framing", num_records - len(records),
                "record length header overruns the chunk")
        (n,) = struct.unpack_from("<I", payload, off)
        off += 4
        if off + n > size:
            return records, _ChunkDamage(
                "framing", num_records - len(records),
                "record payload overruns the chunk (len=%d)" % n)
        records.append(payload[off:off + n])
        off += n
    return records, None


def _record_damage(path, policy, kind, n_lost, chunk_index, detail,
                   warned, record_index=None):
    """Apply the anomaly policy to `n_lost` damaged records — one
    chunk's loss, or a single undecodable record when `record_index`
    is given (the sample-reader path). ONE dispatch for every damage
    site: abort raise / quarantine / skip telemetry and the once-per-
    shard warning all live here. Returns True when the shard should be
    quarantined (caller stops reading it). Telemetry runs outside any
    lock."""
    n_lost = max(1, int(n_lost))
    _metrics.counter("data/records_corrupt").inc(n_lost)
    where = ("record %d" % record_index if record_index is not None
             else "chunk %s" % chunk_index)
    if policy == DATA_POLICY_ABORT:
        raise DataAnomalyError(path, kind, chunk_index=chunk_index,
                               record_index=record_index, detail=detail)
    if policy == DATA_POLICY_QUARANTINE_SHARD:
        _quarantine(path)
        warnings.warn(
            "data plane: quarantining shard %r (%s, %s: %s)"
            % (path, kind, where, detail), RuntimeWarning)
        return True
    _metrics.counter("data/records_skipped").inc(n_lost)
    if not warned[0]:
        warned[0] = True
        if record_index is not None:
            warnings.warn(
                "data plane: skipping undecodable record %d in shard "
                "%r: %s" % (record_index, path, detail), RuntimeWarning)
        else:
            warnings.warn(
                "data plane: skipping ~%d damaged record(s) in shard %r "
                "(%s, %s: %s)" % (n_lost, path, kind, where, detail),
                RuntimeWarning)
    return False


def iter_shard_records(path, shard_index=0, policy=None):
    """Yield the raw records of one recordio shard with corrupt-input
    containment (docs/DATA_PLANE.md): per-chunk CRC, per-record framing
    and truncated-tail damage route through the anomaly policy instead
    of raising mid-epoch. On a healthy shard the emitted stream is
    byte-identical to the native scanner's. `shard_index` keys the
    `data_corrupt_shard:N` / `data_stall_shard:N` injector sites.

    Healthy shards stream through the native C scanner (the legacy
    ingestion speed — the from-spec Python CRC-32C/snappy decoders
    would put a per-byte loop on the hot path for reference-format
    shards); the Python containment reader takes over only at the
    scanner's first damage verdict, skipping the records already
    emitted (the healthy prefix is bitwise-identical across the two
    readers), or when the native library is unavailable."""
    from .core import native
    from .resilience import maybe_inject_shard_fault

    policy = data_anomaly_policy(policy)
    injected = maybe_inject_shard_fault(shard_index)
    if injected == "stall":
        # a slow shard must not wedge the pipeline's determinism —
        # bounded, one-shot (the prefetch window absorbs it)
        time.sleep(0.25)
    force_corrupt = injected == "corrupt"
    skip = 0
    if not force_corrupt:
        scanner = None
        try:
            scanner = native.RecordIOScanner(path)
        except (RuntimeError, IOError):
            scanner = None  # no native lib / unopenable: Python path
        if scanner is not None:
            damaged = False
            try:
                try:
                    for rec in scanner:
                        yield rec
                        skip += 1
                except IOError:
                    # the -2 bad-chunk verdict: re-read under
                    # containment, skipping the emitted prefix
                    damaged = True
            finally:
                scanner.close()
            if not damaged:
                # the one tear the C scanner reads as clean EOF: a
                # sub-magic trailing fragment — still a policy verdict
                frag, chunks = _torn_tail(path)
                if frag:
                    _record_damage(
                        path, policy, "truncated", 1, chunks,
                        "truncated chunk magic (%d byte tail)" % frag,
                        [False])
                return
    warned = [False]
    chunk_index = 0
    with open(path, "rb") as f:
        while True:
            try:
                loaded = _read_chunk(f, force_corrupt=force_corrupt)
            except _ChunkDamage as dmg:
                if _record_damage(path, policy, dmg.kind,
                                  dmg.num_records, chunk_index,
                                  dmg.detail, warned):
                    return
                chunk_index += 1
                continue
            except _ShardTorn as torn:
                # no recoverable boundary past this point: whatever the
                # policy, the rest of the shard is gone — count it as
                # one unknown-size loss and stop
                if _record_damage(path, policy, "truncated", 1,
                                  chunk_index, torn.detail, warned):
                    return
                return
            if loaded is None:
                return
            payload, num = loaded
            records, damage = _split_records(payload, num)
            if skip:
                taken = min(skip, len(records))
                records = records[taken:]
                skip -= taken
            yield from records
            if damage is not None and _record_damage(
                    path, policy, damage.kind, damage.num_records,
                    chunk_index, damage.detail, warned):
                return
            chunk_index += 1


def resilient_sample_reader(paths, policy=None, shard_indices=None):
    """Reader creator over recordio shards with containment: yields
    deserialized samples; record-payload damage (`RecordFormatError`
    from a record whose chunk CRC still passed) routes through the same
    policy as chunk damage. Drop-in for
    `recordio_writer.recordio_reader_creator` on the dataset path."""
    if isinstance(paths, str):
        paths = paths.split(",")
    paths = list(paths)
    if shard_indices is None:
        shard_indices = list(range(len(paths)))

    def reader():
        resolved = data_anomaly_policy(policy)
        for shard_index, path in zip(shard_indices, paths):
            warned = [False]
            record_index = 0
            for rec in iter_shard_records(path, shard_index=shard_index,
                                          policy=resolved):
                try:
                    sample = deserialize_sample(rec)
                except RecordFormatError as e:
                    if _record_damage(path, resolved, "record", 1,
                                      None, str(e), warned,
                                      record_index=record_index):
                        break
                    record_index += 1
                    continue
                record_index += 1
                yield sample

    return reader


# ---------------------------------------------------------------------------
# mid-epoch resumable iteration
# ---------------------------------------------------------------------------

_CURSOR_VERSION = 1


def shard_order(n_shards, seed=None, epoch=0):
    """The deterministic per-epoch shard permutation the resumable
    stream reads in: `seed=None` keeps filelist order (the legacy
    contract); otherwise a seeded per-epoch shuffle so multi-epoch runs
    revisit shards in fresh orders while any resume recomputes the
    identical permutation."""
    order = list(range(int(n_shards)))
    if seed is not None:
        random.Random(int(seed) * 1000003 + int(epoch) * 7919).shuffle(
            order)
    return order


class DatasetCursor:
    """A checkpointable position in the deterministic record stream
    (docs/DATA_PLANE.md): the NEXT record the consumer has not seen is
    record `record_offset` of shard `shard_order(n, seed, epoch)
    [shard_idx]` of epoch `epoch`. `Dataset.resumable_batches` advances
    it as batches are consumed; `write_to(scope)` parks it under
    ``__data_cursor__`` so scope snapshots/checkpoints (PR-4 manifest)
    carry it for free and a restored run resumes the byte-identical
    stream."""

    SCOPE_KEY = "__data_cursor__"

    __slots__ = ("epoch", "shard_idx", "record_offset", "seed")

    def __init__(self, epoch=0, shard_idx=0, record_offset=0, seed=None):
        self.epoch = int(epoch)
        self.shard_idx = int(shard_idx)
        self.record_offset = int(record_offset)
        self.seed = None if seed is None else int(seed)

    def position(self):
        return (self.epoch, self.shard_idx, self.record_offset)

    def advance_to(self, epoch, shard_idx, record_offset):
        self.epoch = int(epoch)
        self.shard_idx = int(shard_idx)
        self.record_offset = int(record_offset)
        return self

    def shard_order(self, n_shards, epoch=None):
        return shard_order(n_shards, self.seed,
                           self.epoch if epoch is None else epoch)

    def clone(self):
        return DatasetCursor(self.epoch, self.shard_idx,
                             self.record_offset, self.seed)

    def to_array(self):
        """Checkpoint encoding: one int64 vector (rides any manifest
        that can hold a numpy leaf)."""
        return np.asarray(
            [_CURSOR_VERSION, self.epoch, self.shard_idx,
             self.record_offset, 0 if self.seed is None else 1,
             0 if self.seed is None else self.seed], np.int64)

    @classmethod
    def from_array(cls, arr):
        arr = np.asarray(arr).reshape(-1)
        if arr.size < 6 or int(arr[0]) != _CURSOR_VERSION:
            raise ValueError("unrecognized DatasetCursor encoding %r"
                             % (arr,))
        return cls(epoch=int(arr[1]), shard_idx=int(arr[2]),
                   record_offset=int(arr[3]),
                   seed=int(arr[5]) if int(arr[4]) else None)

    def write_to(self, scope):
        scope.set(self.SCOPE_KEY, self.to_array())
        return self

    @classmethod
    def from_scope(cls, scope):
        """The cursor a restored scope carries, or None when the run
        never used one."""
        val = scope.get(cls.SCOPE_KEY)
        if val is None:
            return None
        return cls.from_array(val)

    def __repr__(self):
        return ("DatasetCursor(epoch=%d, shard_idx=%d, record_offset=%d,"
                " seed=%r)" % (self.epoch, self.shard_idx,
                               self.record_offset, self.seed))


def apply_cursor(pairs, cursor, scope=None):
    """Consumer-side cursor application: `pairs` yields
    `(batch, (epoch, shard_idx, record_offset))` — possibly through a
    prefetch queue — and the cursor (plus its scope mirror) advances
    only when the CONSUMER takes the batch. Batches still sitting in
    the prefetch queue never move the cursor, so a checkpoint taken
    mid-stream names exactly the first unconsumed record (the
    prefetcher drain state is implicit)."""
    for batch, state in pairs:
        cursor.advance_to(*state)
        if scope is not None:
            cursor.write_to(scope)
        yield batch
