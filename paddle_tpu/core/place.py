"""Device places (parity: paddle/fluid/platform/place.h, bound at
pybind/pybind.cc:886-963).

TPU-native: a Place names a JAX device set, not a CUDA ordinal. TPUPlace is
the accelerator place; CUDAPlace is accepted as an alias so Fluid-style
scripts run unchanged. `CUDAPinnedPlace` maps to host-committed memory used
for async feeds.
"""

import functools


class Place:
    _kind = "base"

    def __eq__(self, other):
        return type(self) is type(other) and getattr(self, "device_id", 0) == getattr(
            other, "device_id", 0
        )

    def __hash__(self):
        return hash((self._kind, getattr(self, "device_id", 0)))

    def __repr__(self):
        if hasattr(self, "device_id"):
            return "%s(%d)" % (type(self).__name__, self.device_id)
        return "%s()" % type(self).__name__


class CPUPlace(Place):
    _kind = "cpu"

    def jax_device(self):
        import jax

        # local (addressable) devices: under a multi-process DCN runtime
        # jax.devices() is global and rank>0 must not target rank 0's device
        cpus = (jax.local_devices(backend="cpu") if _has_platform("cpu")
                else jax.local_devices())
        return cpus[0]


class TPUPlace(Place):
    """The accelerator place. device_id indexes jax.devices()."""

    _kind = "tpu"

    def __init__(self, device_id=0):
        self.device_id = device_id

    def jax_device(self):
        import jax

        devs = jax.local_devices()
        return devs[self.device_id % len(devs)]


class CUDAPlace(TPUPlace):
    """Alias of TPUPlace for Fluid source compatibility (place.h CUDAPlace)."""

    _kind = "tpu"


class CUDAPinnedPlace(CPUPlace):
    _kind = "pinned"


@functools.lru_cache(maxsize=None)
def _has_platform(name):
    import jax

    try:
        return len(jax.devices(name)) > 0
    except RuntimeError:
        return False


def default_place():
    """Accelerator if present, else CPU."""
    import jax

    d = jax.devices()[0]
    if d.platform == "cpu":
        return CPUPlace()
    return TPUPlace(0)
