"""LoDTensor / LoDTensorArray at the API edge (parity:
framework/lod_tensor.h:58-110; pybind.cc:396).

TPU-native stance (SURVEY §5.7): ragged sequences are represented as padded
dense arrays + explicit per-sequence lengths; the LoD offset table is kept on
the host wrapper for API parity and converted to masks/segment-ids by the
sequence ops."""

import numpy as np

__all__ = ["LoDTensor", "LoDTensorArray", "create_lod_tensor",
           "create_random_int_lodtensor"]


class LoDTensor:
    def __init__(self, array=None, lod=None):
        self._array = np.asarray(array) if array is not None else None
        self._lod = lod or []

    def set(self, array, place=None):
        self._array = np.asarray(array)

    def set_lod(self, lod):
        self._lod = lod

    def lod(self):
        return self._lod

    def set_recursive_sequence_lengths(self, lengths):
        # convert lengths to offsets
        lod = []
        for lv in lengths:
            offs = [0]
            for n in lv:
                offs.append(offs[-1] + n)
            lod.append(offs)
        self._lod = lod

    def recursive_sequence_lengths(self):
        out = []
        for offs in self._lod:
            out.append([offs[i + 1] - offs[i] for i in range(len(offs) - 1)])
        return out

    def has_valid_recursive_sequence_lengths(self):
        if not self._lod:
            return True
        n = self._array.shape[0] if self._array is not None else 0
        return self._lod[-1][-1] == n

    def shape(self):
        return list(self._array.shape) if self._array is not None else []

    def __array__(self, dtype=None):
        a = self._array
        return a.astype(dtype) if dtype else a

    def __repr__(self):
        return "LoDTensor(shape=%s, lod=%s)" % (self.shape(), self._lod)


class LoDTensorArray(list):
    def append_tensor(self, t):
        self.append(t)


def create_lod_tensor(data, recursive_seq_lens, place=None):
    t = LoDTensor(np.asarray(data))
    t.set_recursive_sequence_lengths(recursive_seq_lens)
    return t


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place=None,
                                low=0, high=1):
    """Random-int LoDTensor whose leading dim is the total of the last
    LoD level (parity: python/paddle/fluid/lod_tensor.py
    create_random_int_lodtensor)."""
    total = sum(recursive_seq_lens[-1])
    shape = [total] + list(base_shape)
    data = np.random.randint(low, high + 1, shape).astype("int64")
    return create_lod_tensor(data, recursive_seq_lens, place)
