"""Program (de)serialization — JSON descriptor layer (parity:
framework/framework.proto + program_desc.cc round-trip; used by
save/load_inference_model).

Grad ops carrying live `__fwd_op__` references are re-linked after load via
the recorded forward-op index.
"""

import json

import numpy as np

from .. import framework


def program_to_desc(program):
    # single canonical serializer: Block.to_desc / Operator.to_desc
    # (framework.py) — keep attr handling in ONE place
    return {"version": 1, "random_seed": program.random_seed,
            "blocks": [blk.to_desc() for blk in program.blocks]}


def program_from_desc(desc):
    p = framework.Program()
    p.random_seed = desc.get("random_seed", 0)
    p.blocks = []
    for bd in desc["blocks"]:
        blk = framework.Block(p, bd["idx"], bd["parent_idx"])
        p.blocks.append(blk)
    for bd, blk in zip(desc["blocks"], p.blocks):
        for vd in bd["vars"]:
            common = dict(
                name=vd["name"],
                shape=vd["shape"],
                dtype=vd["dtype"],
                lod_level=vd.get("lod_level", 0),
                stop_gradient=vd.get("stop_gradient", False),
                is_data=vd.get("is_data", False),
                type=vd.get("type"),
            )
            if vd.get("is_parameter"):
                v = framework.Parameter(
                    blk, shape=common.pop("shape"),
                    dtype=common.pop("dtype"),
                    trainable=vd.get("trainable", True), **common)
            else:
                v = framework.Variable(
                    blk, persistable=vd.get("persistable", False), **common)
            blk.vars[v.name] = v
    for bd, blk in zip(desc["blocks"], p.blocks):
        for od in bd["ops"]:
            attrs = {}
            for k, v in od["attrs"].items():
                if isinstance(v, dict) and "__block__" in v:
                    attrs[k] = p.blocks[v["__block__"]]
                elif isinstance(v, dict) and "__ndarray__" in v:
                    attrs[k] = np.asarray(v["__ndarray__"], dtype=v["dtype"])
                else:
                    attrs[k] = v
            blk.append_op(
                type=od["type"],
                inputs={k: [blk.var(n) for n in ns]
                        for k, ns in od["inputs"].items()},
                outputs={k: [blk.var(n) for n in ns]
                         for k, ns in od["outputs"].items()},
                attrs=attrs,
            )
    # re-link grad ops to their forward ops
    for blk in p.blocks:
        for op in blk.ops:
            ref = op.attrs.get("__fwd_op__")
            if isinstance(ref, dict) and "__op_index__" in ref:
                op.attrs["__fwd_op__"] = \
                    p.blocks[ref["__op_block__"]].ops[ref["__op_index__"]]
    p.current_block_idx = 0
    return p


def program_from_json(s):
    return program_from_desc(json.loads(s))
