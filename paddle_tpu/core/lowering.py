"""Program -> JAX lowering (the TPU-native replacement for Fluid's op-by-op
Executor hot loop, framework/executor.cc:387-450).

Instead of interpreting ops over mutable scopes, `execute_block` symbolically
runs every op's JAX kernel over an environment of tracers; the whole block
(forward + grad ops + optimizer ops) becomes ONE traced function that XLA
compiles and fuses. Gradient ops are generic: a grad op re-runs its forward
op's kernel under `jax.vjp` and applies the upstream cotangents — duplicate
forward computation is eliminated by XLA CSE inside the single jitted step,
which replaces Fluid's ~400 hand-written grad kernels
(framework/grad_op_desc_maker.h machinery).
"""

from contextlib import contextmanager

import jax
import jax.numpy as jnp
import numpy as np

from ..observability import metrics as _obs_metrics
from ..ops import registry


class LoweringContext:
    """Per-trace context handed to op kernels.

    rng(attrs): deterministic per-op PRNG key — folded from (program seed,
    op seed, step counter), so dropout masks differ across steps but the
    grad op's forward recompute sees the identical mask (same fold inputs).
    """

    def __init__(self, base_key, is_test=False, data_axis=None, mesh=None,
                 check_nan_inf=False):
        self.base_key = base_key
        self.is_test = is_test
        # mesh axis name along which data-parallel collectives run (pmean in
        # sync_batch_norm etc.); None outside shard_map/pmap tracing
        self.data_axis = data_axis
        self.mesh = mesh
        # FLAGS_check_nan_inf parity (operator.cc:950): when on, every
        # floating op output contributes an isfinite-all flag; the executor
        # raises host-side naming the first offending op/var
        self.check_nan_inf = check_nan_inf
        self.nan_reports = []   # list of (label, bool scalar tracer)
        # always-on runtime warnings: (message, bool tracer) where True
        # means "warn" — e.g. a While whose max_trip_count truncated the
        # loop with the condition still live. Packed alongside fetches;
        # the executor warns host-side (once per site).
        self.warn_reports = []
        self._nan_suppress = 0
        # sharding-planner hooks (parallel/planner.py): activation seams
        # {var name: NamedSharding} applied via with_sharding_constraint
        # where the var is produced, and the GradientScaleStrategy factor
        # folded into the backward seed (ops/math.py fill_any_like)
        self.act_constraints = {}
        self.grad_seed_scale = 1.0
        # True inside a pipeline stage branch (parallel/pipeline_program):
        # only the resident stage's ranks execute there, so op lowerings
        # must avoid PAIR-style collectives (ppermute/all-to-all — their
        # rendezvous spans every device); group-style psum/all_gather are
        # per-group and safe. The flash_attention op switches its
        # sequence-parallel lowering from ring to all-gather on this flag.
        self.no_pair_collectives = False
        # forward input values per op, captured at forward-execution time.
        # Grad ops recompute their forward under jax.vjp; reading inputs
        # from the *current* env would be wrong whenever a var was
        # overwritten after the op ran (in-place writes — While carries,
        # increment, assign-into). Holding tracer refs costs nothing in
        # the jaxpr unless a grad op actually uses them.
        self.fwd_snapshots = {}

    @contextmanager
    def inner_trace(self):
        """Suppress nan-report collection while lowering a control-flow
        sub-block (lax.while_loop/cond/scan body): values produced there are
        tracers of the INNER trace and may not leak into the outer step's
        nan_reports. The control-flow op's own outputs are still checked by
        `_bind_outputs` in the outer trace."""
        self._nan_suppress += 1
        try:
            yield
        finally:
            self._nan_suppress -= 1

    def rng(self, attrs):
        seed = attrs.get("__op_seed__")
        if seed is None:
            seed = attrs.get("seed", 0) or 0
        return jax.random.fold_in(self.base_key, int(seed) & 0x7FFFFFFF)


# ops that are pure program structure — no runtime kernel
_STRUCTURAL = {"feed", "fetch", "read", "double_buffer", "create_py_reader",
               "data", "depend",
               # pserver RPC ops (transpiler/distribute_transpiler.py) are
               # invisible to the jitted step: the EXECUTOR runs them
               # host-side after each step (executor._run_rpc_plan over
               # distributed_runtime.ParameterServerClient), and a program
               # holding listen_and_serv blocks in run_pserver
               "send", "recv", "send_barrier", "fetch_barrier",
               "listen_and_serv", "checkpoint_notify", "gen_nccl_id"}

# ops with bespoke lowering (control flow etc.) — populated by
# ops/controlflow.py via register_special
_SPECIAL = {}


def register_special(op_type):
    def deco(fn):
        _SPECIAL[op_type] = fn
        return fn

    return deco


def execute_block(block, env, ctx):
    """Symbolically execute every op of `block` over env (name -> tracer)."""
    if _obs_metrics.enabled():
        # trace-time (not per-step) cost: these count how much program
        # structure each retrace lowers, the denominator for compile-time
        # histograms in the compile cache telemetry
        _obs_metrics.counter("lowering/blocks_traced").inc()
        _obs_metrics.counter("lowering/ops_traced").inc(len(block.ops))
    for op in block.ops:
        execute_op(op, env, ctx)
    return env


def _op_scope_name(op):
    """Stable profiler identity for one descriptor op: type plus its first
    output var (sanitized). jax.named_scope threads this through HLO
    metadata, so device traces map back to Fluid op names (the reference
    tags kernels via platform::RecordEvent in operator.cc:180-184)."""
    out = ""
    for vs in op.outputs.values():
        if vs:
            out = vs[0].name
            break
    name = "%s__%s" % (op.type, out) if out else op.type
    return "".join(c if (c.isalnum() or c in "_.-") else "_" for c in name)


def execute_op(op, env, ctx):
    if op.type in _STRUCTURAL:
        return
    if op.type in _SPECIAL:
        with jax.named_scope("fluid/" + _op_scope_name(op)):
            _SPECIAL[op.type](op, env, ctx)
        return
    if "__fwd_op__" in op.attrs:
        with jax.named_scope("fluid/" + _op_scope_name(op)):
            _execute_grad_op(op, env, ctx)
        return
    opdef = registry.get(op.type)

    def _val(v):
        # a tensor array created empty (layers.create_array) has no
        # producing op, so its first mention inside a loop finds no env
        # binding — it IS the empty array
        if v.name not in env and getattr(v, "is_tensor_array", False):
            return []
        return env[v.name]

    ins = {
        slot: [_val(v) for v in vs] for slot, vs in op.inputs.items() if vs
    }
    if opdef.differentiable:
        ctx.fwd_snapshots[id(op)] = ins
    with jax.named_scope("fluid/" + _op_scope_name(op)):
        outs = opdef.impl(ctx, ins, op.attrs)
    _bind_outputs(op, outs, env, ctx)


def _nan_check(ctx, label, val):
    if ctx._nan_suppress:
        return
    try:
        dt = jnp.result_type(val)
    except TypeError:
        return
    if jnp.issubdtype(dt, jnp.inexact):
        ctx.nan_reports.append((label, jnp.isfinite(val).all()))


def pack_warn_reports(ctx):
    """(static labels, packed bool tracer) for runtime warnings."""
    labels = [label for label, _ in ctx.warn_reports]
    flags = (jnp.stack([f for _, f in ctx.warn_reports])
             if ctx.warn_reports else jnp.zeros((0,), bool))
    return labels, flags


def pack_nan_reports(ctx):
    """Collapse ctx.nan_reports into (static labels, packed bool tracer) for
    a jitted step to return alongside its outputs."""
    labels = [label for label, _ in ctx.nan_reports]
    finite = (jnp.stack([f for _, f in ctx.nan_reports])
              if ctx.nan_reports else jnp.ones((0,), bool))
    return labels, finite


def raise_if_nonfinite(labels, finite):
    """Host-side FLAGS_check_nan_inf raise (operator.cc:950 parity), naming
    the offending op outputs. Callers must NOT donate the step's state when
    the flag is on: raising before write-back then leaves the scope at its
    pre-step values, discarding the poisoned update."""
    finite_np = np.asarray(finite)
    if finite_np.all():
        return
    bad = [label for label, ok in zip(labels, finite_np) if not ok]
    raise RuntimeError(
        "Operator output contains Inf/Nan (FLAGS_check_nan_inf): "
        + "; ".join(bad[:8]))


def _bind_outputs(op, outs, env, ctx=None):
    for slot, vs in op.outputs.items():
        if not vs:
            continue
        produced = outs.get(slot)
        if produced is None:
            continue
        for v, val in zip(vs, produced):
            if ctx is not None and ctx.act_constraints:
                sh = ctx.act_constraints.get(v.name)
                if sh is not None:
                    val = jax.lax.with_sharding_constraint(val, sh)
            env[v.name] = val
            if ctx is not None and ctx.check_nan_inf:
                _nan_check(ctx, "%s -> %s" % (op.type, v.name), val)


def _zero_cotangent(primal):
    if jnp.issubdtype(jnp.result_type(primal), jnp.inexact):
        return jnp.zeros_like(primal)
    # integer/bool primals take float0 cotangents
    return np.zeros(np.shape(primal), dtype=jax.dtypes.float0)


def _base_fwd(op):
    while "__fwd_op__" in op.attrs:
        op = op.attrs["__fwd_op__"]
    return op


def _op_impl_fn(op, ctx):
    """(impl, nondiff_inputs) for ANY op — primitive (registry kernel) or
    gradient. A grad op's impl purely maps its inputs (forward inputs +
    upstream cotangents) to its InputGrads outputs via `_grad_apply`; giving
    grad ops the same impl(ctx, ins, attrs) signature as primitives is what
    makes higher-order differentiation compose — append_backward
    differentiates a grad op like any other op and JAX traces
    reverse-over-reverse (the reference hand-registers *_grad_grad kernels
    per op, elementwise_add_op.cc:23-72; here every op gets one at once)."""
    if "__fwd_op__" not in op.attrs:
        opdef = registry.get(op.type)
        return opdef.impl, opdef.nondiff_inputs

    out_vars = list(op.outputs.get("InputGrads", ()))

    def impl(ctx2, ins, attrs):
        produced = _grad_apply(op, ins, ctx2)
        return {"InputGrads": [produced.get(v.name) for v in out_vars]}

    return impl, registry.get(_base_fwd(op).type).nondiff_inputs


def _cot_slot_map(op):
    """{forward output slot: grad-op input slot carrying its cotangents}."""
    m = op.attrs.get("__cot_slots__")
    if m is not None:
        return m
    fwd = op.attrs["__fwd_op__"]
    return {s[: -len("@GRAD")]: s for s in op.inputs
            if s.endswith("@GRAD") and s not in fwd.inputs}


def _gather_grad_ins(op, env, ctx):
    """Collect a grad op's input values: forward-op inputs from the
    forward-time snapshot (env values may have been overwritten by in-place
    writes since), upstream cotangents from env (None = dead: that grad var
    was never produced — e.g. its producer pruned all its outputs)."""
    fwd = op.attrs["__fwd_op__"]
    cot_slot_names = set(_cot_slot_map(op).values())
    snap = ctx.fwd_snapshots.get(id(fwd))
    ins = {}
    for slot, vs in op.inputs.items():
        if not vs:
            continue
        if slot in cot_slot_names:
            ins[slot] = [env.get(v.name) for v in vs]
        elif snap is not None and slot in snap:
            ins[slot] = snap[slot]
        else:
            ins[slot] = [env[v.name] for v in vs]
    return ins


def _grad_apply(gop, ins, ctx):
    """Pure generic gradient kernel: vjp of the forward op's impl.

    gop.attrs carries:
      __fwd_op__       : the forward Operator (possibly itself a grad op)
      __grad_out_map__ : {slot: [grad var name or None per output]}
      __grad_in_map__  : {slot: [grad var name or None per input]}

    `ins` is the grad op's full input dict (slot -> list of values; None
    marks a dead cotangent). Returns {grad var name: value} with duplicate
    contributions (a var feeding the op twice) pre-summed. Pure in `ins`,
    so a grad op can itself be differentiated by an outer jax.vjp."""
    fwd = gop.attrs["__fwd_op__"]
    gout_map = gop.attrs["__grad_out_map__"]
    gin_map = gop.attrs["__grad_in_map__"]
    impl, nondiff = _op_impl_fn(fwd, ctx)
    cot_slot_names = set(_cot_slot_map(gop).values())

    fwd_ins = {s: v for s, v in ins.items() if s not in cot_slot_names}
    diff_slots = [
        s
        for s in fwd_ins
        if s not in nondiff
        and any(
            x is not None
            and jnp.issubdtype(jnp.result_type(x), jnp.inexact)
            for x in fwd_ins[s]
        )
    ]
    const_ins = {s: v for s, v in fwd_ins.items() if s not in diff_slots}
    diff_ins = {s: fwd_ins[s] for s in diff_slots}

    # upstream cotangent values: ins[cot_slot] aligns with the non-None
    # entries of gout_map[out_slot]
    cot_by_idx = {}
    for out_slot, cslot in _cot_slot_map(gop).items():
        names = gout_map.get(out_slot, [])
        idxs = [i for i, g in enumerate(names) if g is not None]
        for i, val in zip(idxs, ins.get(cslot, [])):
            if val is not None:
                cot_by_idx.setdefault(out_slot, {})[i] = val

    # Only differentiate through outputs that actually carry an upstream
    # cotangent. Taking the vjp over EVERY output would make jax save
    # residuals for dead ones too — e.g. softmax_with_cross_entropy's
    # Softmax side output (a full fp32 [B, T, vocab] buffer for an LM
    # head) or layer_norm's Mean/Variance — which XLA then materializes
    # in the forward even though the dead outputs' zero cotangents fold
    # away in the backward.
    #
    # Probe output structure ABSTRACTLY (eval_shape emits no HLO): a real
    # re-execution would duplicate the forward — for control-flow ops a
    # whole second lax.scan/while that XLA cannot CSE across loop
    # boundaries. inner_trace suppresses warn/nan collection, which would
    # otherwise capture the probe's abstract tracers.
    with ctx.inner_trace():
        probe = jax.eval_shape(
            lambda d: impl(ctx, d, fwd.attrs), fwd_ins)
    live_idx = {}
    for slot, prim_list in probe.items():
        idx = [i for i, prim in enumerate(prim_list)
               if prim is not None
               and i in cot_by_idx.get(slot, ())
               and jnp.issubdtype(jnp.result_type(prim), jnp.inexact)]
        if idx:
            live_idx[slot] = idx
    if not live_idx:
        return {}

    def f(d):
        outs = impl(ctx, {**const_ins, **d}, fwd.attrs)
        return {slot: [outs[slot][i] for i in idx]
                for slot, idx in live_idx.items()}

    primal_out, vjp_fn = jax.vjp(f, diff_ins)

    cots = {}
    for slot, prim_list in primal_out.items():
        cot_list = []
        for j, prim in enumerate(prim_list):
            g = cot_by_idx[slot][live_idx[slot][j]]
            cot_list.append(g.astype(jnp.result_type(prim)))
        cots[slot] = cot_list
    (gd,) = vjp_fn(cots)

    produced = {}
    for slot in diff_slots:
        names = gin_map.get(slot, [])
        for i, g in enumerate(gd[slot]):
            gname = names[i] if i < len(names) else None
            if gname is None or g is None:
                continue
            if isinstance(g, np.ndarray) and g.dtype == jax.dtypes.float0:
                continue
            if gname in produced:
                produced[gname] = produced[gname] + g
            else:
                produced[gname] = g
    return produced


def _execute_grad_op(op, env, ctx):
    """Executor entry for grad ops: gather inputs, run the pure kernel,
    scatter produced grads into env (accumulating on the __accumulate__
    tags append_backward computed)."""
    ins = _gather_grad_ins(op, env, ctx)
    # snapshot so THIS grad op can itself be differentiated by a later
    # backward pass (fluid.gradients of a gradient)
    ctx.fwd_snapshots[id(op)] = ins
    produced = _grad_apply(op, ins, ctx)
    accumulate = op.attrs.get("__accumulate__", {})
    for gname, g in produced.items():
        if gname in env and accumulate.get(gname):
            env[gname] = env[gname] + g
        else:
            env[gname] = g
        if ctx.check_nan_inf:
            _nan_check(ctx, "%s -> %s" % (op.type, gname), env[gname])
