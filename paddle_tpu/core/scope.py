"""Scope: name -> value store at the API edge (parity:
framework/scope.h:45 — but only at the edge: inside a jitted step all state
is a functional pytree; the Scope holds the device-resident persistable
arrays between steps).
"""

import numpy as np

__all__ = ["Scope", "global_scope", "scope_guard"]


class Scope:
    def __init__(self, parent=None):
        self._vars = {}
        self.parent = parent
        self._kids = []

    def var(self, name):
        """Create (or get) a slot for `name`."""
        if name not in self._vars:
            self._vars[name] = None
        return _VarHandle(self, name)

    def find_var(self, name):
        s = self
        while s is not None:
            if name in s._vars:
                return _VarHandle(s, name)
            s = s.parent
        return None

    def new_scope(self):
        k = Scope(self)
        self._kids.append(k)
        return k

    def drop_kids(self):
        self._kids = []

    # -- raw value access used by the executor -----------------------------
    def get(self, name, default=None):
        s = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s.parent
        return default

    def set(self, name, value):
        self._vars[name] = value

    def erase(self, name):
        """Drop this scope's OWN binding of `name` if present (parity:
        framework/scope.cc Scope::EraseVars — ancestor bindings are never
        touched, so a child scope can never delete a var it doesn't own)."""
        self._vars.pop(name, None)

    def erase_nearest(self, name):
        """Drop the binding `get` would return — walks ancestors to the
        owning scope and erases there (for transforms that must retire a
        var wherever startup placed it, e.g. the quantize transpiler)."""
        s = self
        while s is not None:
            if name in s._vars:
                del s._vars[name]
                return
            s = s.parent

    def has(self, name):
        return self.get(name, _MISSING) is not _MISSING

    def local_var_names(self):
        return list(self._vars)

    def items(self):
        """This scope's OWN (name, value) bindings — the state surface
        resilience.snapshot_scope copies to host for rollback/checkpoint
        (ancestor bindings belong to their owning scope's snapshot)."""
        return list(self._vars.items())

    def __contains__(self, name):
        return self.has(name)


_MISSING = object()


class _VarHandle:
    """Fluid-style Variable handle into a scope slot."""

    def __init__(self, scope, name):
        self._scope = scope
        self._name = name

    def name(self):
        return self._name

    def get_tensor(self):
        return _TensorHandle(self._scope, self._name)

    def get_value(self):
        return self._scope.get(self._name)

    def set_value(self, v):
        self._scope.set(self._name, v)


class _TensorHandle:
    def __init__(self, scope, name):
        self._scope = scope
        self._name = name

    def set(self, array, place=None):
        self._scope.set(self._name, np.asarray(array))

    def shape(self):
        v = self._scope.get(self._name)
        return list(np.shape(v)) if v is not None else []

    def __array__(self, dtype=None):
        v = np.asarray(self._scope.get(self._name))
        return v.astype(dtype) if dtype else v


_global_scope = Scope()
_scope_stack = [_global_scope]


def global_scope():
    return _scope_stack[-1]


class scope_guard:
    def __init__(self, scope):
        self._scope = scope

    def __enter__(self):
        _scope_stack.append(self._scope)
        return self._scope

    def __exit__(self, *a):
        _scope_stack.pop()
