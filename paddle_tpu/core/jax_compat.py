"""Version-bridging shims over jax.

The codebase targets the current jax API; the runtime image may carry an
older release. Each shim degrades to the old spelling with identical
semantics so the parallel paths run on both.

shard_map: `jax.shard_map` (top-level since jax 0.6) vs
`jax.experimental.shard_map.shard_map`. Keyword drift handled:
  check_vma=...      -> check_rep=...   (the replication/varying-manual-
                                         axes check was renamed)
  axis_names={...}   -> auto=mesh axes - axis_names  (partial-manual:
                        the new API names the MANUAL axes, the old one
                        names the AUTO remainder)

axis_index: on jaxlib < 0.5, `jax.lax.axis_index` inside a PARTIAL-auto
shard_map region lowers to a PartitionId HLO instruction old XLA rejects
under SPMD partitioning (XlaRuntimeError UNIMPLEMENTED — ROADMAP
jax-version drift). There is no in-region workaround on that XLA:
collective-based rank derivations (psum_scatter, asymmetric ppermute) and
even the region's ordinary ppermutes CHECK-abort the whole process in the
old SPMD partitioner once PartitionId is out of the way (measured on
jaxlib 0.4.36: `Check failed: sharding.IsManualSubgroup()`), which is
strictly worse than the UNIMPLEMENTED raise. So the shim keeps the native
primitive — one routing point for when a lowering-level fix exists — and
exports AXIS_INDEX_SAFE_UNDER_PARTIAL_AUTO for the version-gated xfails
on the affected sp/pp-combo tests (the raise is the loud, catchable
failure mode; the tests document it instead of polluting tier-1).
"""

try:  # jax >= 0.6
    from jax import shard_map as _shard_map

    _NEW_API = True
except ImportError:
    from jax.experimental.shard_map import shard_map as _shard_map

    _NEW_API = False

import jaxlib.version

_JAXLIB_VERSION = tuple(
    int(p) for p in jaxlib.version.__version__.split(".")[:2])

# PartitionId under partial-auto SPMD partitioning is supported by the
# XLA bundled with jaxlib >= 0.5
AXIS_INDEX_SAFE_UNDER_PARTIAL_AUTO = _JAXLIB_VERSION >= (0, 5)

# Cross-process collectives on the CPU backend ("Multiprocess
# computations aren't implemented on the CPU backend"): the old XLA:CPU
# client has no cross-process collective implementation, so
# jax.distributed multi-host runs CHECK out at the first psum. Landed
# with the thread-pool collectives rework shipped in jaxlib >= 0.5; the
# multi-process CPU tests are version-gated on this probe, mirroring
# AXIS_INDEX_SAFE_UNDER_PARTIAL_AUTO.
MULTIPROCESS_CPU_COLLECTIVES = _JAXLIB_VERSION >= (0, 5)

__all__ = ["shard_map", "optimization_barrier", "axis_index",
           "compiled_cost_analysis", "compiled_memory_analysis",
           "AXIS_INDEX_SAFE_UNDER_PARTIAL_AUTO",
           "MULTIPROCESS_CPU_COLLECTIVES"]


def compiled_cost_analysis(compiled):
    """Normalized ``{metric: float}`` from an XLA executable's
    ``cost_analysis()`` ('flops', 'bytes accessed', ...). jaxlib 0.4
    returns a per-device LIST of dicts, newer releases a plain dict, and
    some backends expose nothing — version drift is a data gap here
    (return None), never an error, so instrumentation can call this
    unconditionally on every compile-cache miss."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out = {}
    for k, v in ca.items():
        try:
            out[str(k)] = float(v)
        except (TypeError, ValueError):
            continue
    return out or None


def compiled_memory_analysis(compiled):
    """Normalized buffer-footprint dict from ``memory_analysis()``
    (CompiledMemoryStats fields, in bytes), or None where the backend/
    jaxlib doesn't expose it. Same data-gap contract as
    :func:`compiled_cost_analysis`."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out = {}
    for field in ("argument_size_in_bytes", "output_size_in_bytes",
                  "temp_size_in_bytes", "alias_size_in_bytes",
                  "generated_code_size_in_bytes"):
        v = ma.get(field) if isinstance(ma, dict) else \
            getattr(ma, field, None)
        if v is None:
            continue
        try:
            out[field] = float(v)
        except (TypeError, ValueError):
            continue
    return out or None


def _make_optimization_barrier():
    """jax.lax.optimization_barrier has no differentiation rule before
    jax 0.5. The barrier is semantically identity and exists only as a
    fusion hint, so on old jax it degrades to identity — every op
    (including double-grad, which custom_vjp cannot express) stays
    differentiable at the cost of the fusion break."""
    import jax
    import numpy as np

    bar = jax.lax.optimization_barrier
    try:
        jax.eval_shape(jax.grad(lambda x: bar(x)), np.zeros((), np.float32))
        return bar
    except NotImplementedError:
        return lambda x: x


optimization_barrier = _make_optimization_barrier()


def axis_index(axis_name):
    """Routing point for jax.lax.axis_index (see module docstring): all
    in-tree shard_map bodies call this instead of the primitive, so a
    future jaxlib-specific lowering fix lands in exactly one place."""
    import jax

    return jax.lax.axis_index(axis_name)


def shard_map(f, mesh=None, in_specs=None, out_specs=None, **kwargs):
    if _NEW_API:
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kwargs)
    kw = dict(kwargs)
    if "check_vma" in kw:
        kw["check_rep"] = kw.pop("check_vma")
    axis_names = kw.pop("axis_names", None)
    if axis_names is not None:
        kw["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)
