"""ctypes binding to the C++ runtime spine (native/ — SURVEY §2.4).

Loads libpaddle_tpu_native.so, building it with `make` on first use if the
checkout has a toolchain. Every consumer degrades gracefully to a pure-
Python fallback when the library is unavailable (`native.lib() is None`),
so the framework works on toolchain-less hosts; with the library, record
IO / reader queues / profiling / program framing run in C++.
"""

import ctypes
import os
import subprocess
import threading

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_LIB_NAME = "libpaddle_tpu_native.so"

_lock = threading.Lock()
_lib = None
_tried = False


def _configure(lib):
    lib.ptpu_recordio_writer_open.restype = ctypes.c_void_p
    lib.ptpu_recordio_writer_open.argtypes = [ctypes.c_char_p,
                                              ctypes.c_uint64,
                                              ctypes.c_uint64]
    lib.ptpu_recordio_writer_open2.restype = ctypes.c_void_p
    lib.ptpu_recordio_writer_open2.argtypes = [ctypes.c_char_p,
                                               ctypes.c_uint64,
                                               ctypes.c_uint64,
                                               ctypes.c_uint32]
    lib.ptpu_recordio_writer_write.restype = ctypes.c_int
    lib.ptpu_recordio_writer_write.argtypes = [ctypes.c_void_p,
                                               ctypes.c_char_p,
                                               ctypes.c_uint64]
    lib.ptpu_recordio_writer_close.restype = ctypes.c_int
    lib.ptpu_recordio_writer_close.argtypes = [ctypes.c_void_p]
    lib.ptpu_recordio_scanner_open.restype = ctypes.c_void_p
    lib.ptpu_recordio_scanner_open.argtypes = [ctypes.c_char_p]
    lib.ptpu_recordio_scanner_next.restype = ctypes.c_int64
    lib.ptpu_recordio_scanner_next.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_char))]
    lib.ptpu_recordio_scanner_close.argtypes = [ctypes.c_void_p]

    lib.ptpu_queue_create.restype = ctypes.c_void_p
    lib.ptpu_queue_create.argtypes = [ctypes.c_uint64]
    lib.ptpu_queue_push.restype = ctypes.c_int
    lib.ptpu_queue_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_uint64, ctypes.c_int]
    lib.ptpu_queue_pop.restype = ctypes.c_int64
    lib.ptpu_queue_pop.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_char)),
        ctypes.c_int]
    lib.ptpu_queue_size.restype = ctypes.c_uint64
    lib.ptpu_queue_size.argtypes = [ctypes.c_void_p]
    lib.ptpu_queue_close.argtypes = [ctypes.c_void_p]
    lib.ptpu_queue_destroy.argtypes = [ctypes.c_void_p]

    lib.ptpu_allocator_create.restype = ctypes.c_void_p
    lib.ptpu_allocator_create.argtypes = [ctypes.c_uint64, ctypes.c_uint64]
    lib.ptpu_alloc.restype = ctypes.c_void_p
    lib.ptpu_alloc.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.ptpu_free.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    for fn in ("ptpu_allocator_in_use", "ptpu_allocator_peak",
               "ptpu_allocator_alloc_count"):
        getattr(lib, fn).restype = ctypes.c_uint64
        getattr(lib, fn).argtypes = [ctypes.c_void_p]
    lib.ptpu_allocator_destroy.argtypes = [ctypes.c_void_p]

    lib.ptpu_prof_enable.argtypes = [ctypes.c_int]
    lib.ptpu_prof_enabled.restype = ctypes.c_int
    lib.ptpu_prof_push.argtypes = [ctypes.c_char_p]
    lib.ptpu_prof_mark.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                   ctypes.c_int64]
    lib.ptpu_prof_dump_chrome.restype = ctypes.c_int64
    lib.ptpu_prof_dump_chrome.argtypes = [ctypes.c_char_p]
    lib.ptpu_prof_stat_record.argtypes = [ctypes.c_char_p, ctypes.c_double]
    lib.ptpu_prof_stat_count.restype = ctypes.c_int64
    lib.ptpu_prof_stat_count.argtypes = [ctypes.c_char_p]
    lib.ptpu_prof_stats_dump_json.restype = ctypes.c_int64
    lib.ptpu_prof_stats_dump_json.argtypes = [ctypes.c_char_p]

    lib.ptpu_program_seal.restype = ctypes.c_int64
    lib.ptpu_program_seal.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_char))]
    lib.ptpu_program_unseal.restype = ctypes.c_int64
    lib.ptpu_program_unseal.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_char))]
    lib.ptpu_buf_free.argtypes = [ctypes.POINTER(ctypes.c_char)]
    lib.ptpu_crc32.restype = ctypes.c_uint32
    lib.ptpu_crc32.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.ptpu_version.restype = ctypes.c_char_p
    lib.ptpu_mslot_parse_file.restype = ctypes.c_void_p
    lib.ptpu_mslot_parse_file.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int)]
    lib.ptpu_mslot_num_records.restype = ctypes.c_int64
    lib.ptpu_mslot_num_records.argtypes = [ctypes.c_void_p]
    lib.ptpu_mslot_bad_lines.restype = ctypes.c_int64
    lib.ptpu_mslot_bad_lines.argtypes = [ctypes.c_void_p]
    lib.ptpu_mslot_slot_total.restype = ctypes.c_int64
    lib.ptpu_mslot_slot_total.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ptpu_mslot_copy_int64.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                          ctypes.c_void_p]
    lib.ptpu_mslot_copy_float.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                          ctypes.c_void_p]
    lib.ptpu_mslot_copy_offsets.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                            ctypes.c_void_p]
    lib.ptpu_mslot_free.argtypes = [ctypes.c_void_p]

    lib.ptpu_tensor_frame.restype = ctypes.c_int64
    lib.ptpu_tensor_frame.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_char))]
    lib.ptpu_tensor_unframe.restype = ctypes.c_int64
    lib.ptpu_tensor_unframe.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_char))]
    return lib


def lib():
    """The loaded native library, or None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        path = os.path.abspath(os.path.join(_NATIVE_DIR, _LIB_NAME))
        if not os.path.exists(path):
            try:
                subprocess.run(["make", "-s"], cwd=os.path.dirname(path),
                               check=True, capture_output=True, timeout=120)
            except Exception:
                return None
        try:
            _lib = _configure(ctypes.CDLL(path))
        except OSError:
            _lib = None
        return _lib


def _take_buf(l, ptr, n):
    data = ctypes.string_at(ptr, n)
    l.ptpu_buf_free(ptr)
    return data


def program_seal(payload: bytes) -> bytes:
    """Frame program bytes with magic/version/CRC (framework/version.h
    parity). Pure-python fallback mirrors the same layout."""
    l = lib()
    if l is not None:
        out = ctypes.POINTER(ctypes.c_char)()
        n = l.ptpu_program_seal(payload, len(payload), ctypes.byref(out))
        if n > 0:
            return _take_buf(l, out, n)
    import struct, zlib

    crc = zlib.crc32(payload) & 0xFFFFFFFF
    return (struct.pack("<IIQI", 0x50545047, 1, len(payload), crc) + payload)


def program_unseal(buf: bytes) -> bytes:
    l = lib()
    if l is not None:
        out = ctypes.POINTER(ctypes.c_char)()
        n = l.ptpu_program_unseal(buf, len(buf), ctypes.byref(out))
        if n >= 0:
            return _take_buf(l, out, n)
        raise ValueError("bad program file (code %d: magic/version/crc)" % n)
    import struct, zlib

    if len(buf) < 20:
        raise ValueError("bad program file: truncated")
    magic, version, plen, crc = struct.unpack("<IIQI", buf[:20])
    if magic != 0x50545047:
        raise ValueError("bad program file: magic")
    if version != 1:
        raise ValueError("unsupported program version %d" % version)
    payload = buf[20:20 + plen]
    if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        raise ValueError("bad program file: CRC mismatch")
    return payload


class NativeQueue:
    """Bounded blocking queue of byte blobs backed by C++
    (LoDTensorBlockingQueue parity); falls back to queue.Queue."""

    def __init__(self, capacity):
        self._l = lib()
        if self._l is not None:
            self._q = self._l.ptpu_queue_create(capacity)
            self._py = None
        else:
            import queue as _queue

            self._py = _queue.Queue(maxsize=capacity)
            self._closed = False

    def push(self, data: bytes, timeout_ms=-1):
        if self._py is None:
            return self._l.ptpu_queue_push(self._q, data, len(data),
                                           timeout_ms) == 1
        self._py.put(data)
        return True

    def pop(self, timeout_ms=-1):
        """bytes, or None when closed and drained."""
        if self._py is None:
            out = ctypes.POINTER(ctypes.c_char)()
            n = self._l.ptpu_queue_pop(self._q, ctypes.byref(out), timeout_ms)
            if n == -2:
                return None
            if n < 0:
                raise TimeoutError("queue pop timed out")
            return _take_buf(self._l, out, n)
        item = self._py.get()
        return item  # None sentinel used for close

    def size(self):
        if self._py is None:
            return self._l.ptpu_queue_size(self._q)
        return self._py.qsize()

    def close(self):
        if self._py is None:
            self._l.ptpu_queue_close(self._q)
        else:
            self._py.put(None)

    def __del__(self):
        try:
            if getattr(self, "_py", True) is None and lib() is not None:
                self._l.ptpu_queue_destroy(self._q)
        except Exception:
            pass


class RecordIOWriter:
    """Chunked CRC'd record file writer (recordio/ parity).

    compressor: 0/None = plain, 1/'deflate' = zlib-compressed chunks
    (chunk.cc:79-96 parity; 'snappy' accepted as an alias — the wire
    format is ours, deflate is the bundled codec)."""

    _COMPRESSORS = {None: 0, "": 0, 0: 0, "none": 0,
                    1: 1, "deflate": 1, "snappy": 1}

    def __init__(self, path, max_chunk_records=1000,
                 max_chunk_bytes=1 << 20, compressor=None):
        self._l = lib()
        if self._l is None:
            raise RuntimeError("native library unavailable for RecordIO")
        key = compressor.lower() if isinstance(compressor, str) \
            else compressor
        if key not in self._COMPRESSORS:
            raise ValueError("unknown recordio compressor %r" % compressor)
        self._w = self._l.ptpu_recordio_writer_open2(
            path.encode(), max_chunk_records, max_chunk_bytes,
            self._COMPRESSORS[key])
        if not self._w:
            raise IOError("cannot open %s" % path)

    def write(self, record: bytes):
        if self._l.ptpu_recordio_writer_write(self._w, record,
                                              len(record)) != 0:
            raise IOError("recordio write failed")

    def close(self):
        if self._w:
            rc = self._l.ptpu_recordio_writer_close(self._w)
            self._w = None
            if rc != 0:
                # the final partial chunk flushes inside close: swallowing
                # a failure here would silently truncate the file's tail
                raise IOError("recordio close failed flushing the final "
                              "chunk (rc=%d)" % rc)


class RecordIOScanner:
    def __init__(self, path):
        self._l = lib()
        if self._l is None:
            raise RuntimeError("native library unavailable for RecordIO")
        self._s = self._l.ptpu_recordio_scanner_open(path.encode())
        if not self._s:
            raise IOError("cannot open %s" % path)

    def __iter__(self):
        out = ctypes.POINTER(ctypes.c_char)()
        while True:
            n = self._l.ptpu_recordio_scanner_next(self._s,
                                                   ctypes.byref(out))
            if n == -1:
                return
            if n == -2:
                raise IOError("corrupt recordio chunk (CRC)")
            yield ctypes.string_at(out, n)

    def close(self):
        if self._s:
            self._l.ptpu_recordio_scanner_close(self._s)
            self._s = None


def parse_multislot_columns(path, slot_types):
    """Columnar MultiSlot parse (data_feed.cc MultiSlotDataFeed parity):
    returns (slots, n_rec, bad_lines) where slots is a list of
    (values [total], offsets [n_rec+1]) per slot — NO per-record python
    objects, so batching stays vectorized numpy end to end."""
    import numpy as np

    type_codes = [0 if str(t).startswith(("int", "uint")) else 1
                  for t in slot_types]
    n_slots = len(type_codes)
    l = lib()
    if l is None:
        records, bad = _parse_multislot_py(path, type_codes)
        slots = []
        for s in range(n_slots):
            per = [np.asarray(r[s]).reshape(-1) for r in records]
            offs = np.zeros(len(records) + 1, np.int64)
            np.cumsum([p.shape[0] for p in per], out=offs[1:])
            vals = (np.concatenate(per) if per
                    else np.zeros(0, np.int64 if type_codes[s] == 0
                                  else np.float32))
            slots.append((vals, offs))
        return slots, len(records), bad

    arr = (ctypes.c_int * n_slots)(*type_codes)
    h = l.ptpu_mslot_parse_file(path.encode(), n_slots, arr)
    if not h:
        raise IOError("cannot open %s" % path)
    try:
        n_rec = l.ptpu_mslot_num_records(h)
        bad = l.ptpu_mslot_bad_lines(h)
        slots = []
        for s in range(n_slots):
            total = l.ptpu_mslot_slot_total(h, s)
            offs = np.empty(n_rec + 1, np.int64)
            l.ptpu_mslot_copy_offsets(h, s, offs.ctypes.data_as(
                ctypes.c_void_p))
            if type_codes[s] == 0:
                vals = np.empty(total, np.int64)
                l.ptpu_mslot_copy_int64(h, s, vals.ctypes.data_as(
                    ctypes.c_void_p))
            else:
                vals = np.empty(total, np.float32)
                l.ptpu_mslot_copy_float(h, s, vals.ctypes.data_as(
                    ctypes.c_void_p))
            slots.append((vals, offs))
        return slots, n_rec, int(bad)
    finally:
        l.ptpu_mslot_free(h)


def parse_multislot_file(path, slot_types):
    """Parse a MultiSlot text file with the C++ feed parser (data_feed.cc
    MultiSlotDataFeed parity). slot_types: list of "int64"/"uint64" or
    "float". Returns (records, bad_lines) where records is a list of
    per-record tuples of np arrays (one per slot). Falls back to a pure-
    Python parser when the native library is unavailable."""
    slots, n_rec, bad = parse_multislot_columns(path, slot_types)
    records = []
    for r in range(n_rec):
        records.append(tuple(
            vals[offs[r]:offs[r + 1]] for vals, offs in slots))
    return records, int(bad)


def _parse_multislot_py(path, type_codes):
    """Pure-Python fallback with identical semantics."""
    import numpy as np

    records, bad = [], 0
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            toks = line.split()
            rec, pos, ok = [], 0, True
            for code in type_codes:
                if pos >= len(toks):
                    ok = False
                    break
                try:
                    n = int(toks[pos])
                except ValueError:
                    ok = False
                    break
                if n < 0 or pos + 1 + n > len(toks):
                    ok = False
                    break
                chunk = toks[pos + 1:pos + 1 + n]
                try:
                    rec.append(np.asarray(
                        [int(t) for t in chunk], np.int64) if code == 0
                        else np.asarray([float(t) for t in chunk],
                                        np.float32))
                except (ValueError, OverflowError):
                    # OverflowError: uint64-range hash ids past int64 —
                    # rejected like the native parser's ERANGE check
                    ok = False
                    break
                pos += 1 + n
            if ok and pos == len(toks):
                records.append(tuple(rec))
            else:
                bad += 1
    return records, bad


# ---------------------------------------------------------------------------
# tensor wire framing (sendrecvop_utils.cc / variable_response.cc parity)
# ---------------------------------------------------------------------------

# dtype codes on the wire (stable enumeration; extend APPEND-ONLY)
_DTYPE_CODES = ["float32", "float64", "float16", "bfloat16", "int8",
                "int16", "int32", "int64", "uint8", "bool",
                "uint16", "uint32", "uint64", "complex64", "complex128"]
_TF_MAGIC = 0x50545446  # "PTTF"
_TF_MAX_NDIM = 16


def tensor_frame(arr) -> bytes:
    """Frame a numpy array for the pserver wire: dtype/shape header +
    CRC-checked payload, produced by the C++ runtime (tensor_frame.cc);
    pure-python fallback mirrors the layout bit-for-bit."""
    import numpy as np

    arr = np.asarray(arr)
    try:
        code = _DTYPE_CODES.index(str(arr.dtype))
    except ValueError:
        raise ValueError(
            "dtype %r has no tensor-wire code (supported: %s)"
            % (str(arr.dtype), ", ".join(_DTYPE_CODES)))
    if arr.ndim > _TF_MAX_NDIM:
        raise ValueError(
            "tensor rank %d exceeds the wire limit of %d"
            % (arr.ndim, _TF_MAX_NDIM))
    # shape BEFORE ascontiguousarray: it promotes 0-d to 1-d (ndmin=1)
    shape = (ctypes.c_int64 * arr.ndim)(*arr.shape)
    payload = np.ascontiguousarray(arr).tobytes()
    l = lib()
    if l is not None:
        out = ctypes.POINTER(ctypes.c_char)()
        n = l.ptpu_tensor_frame(payload, len(payload), code, shape,
                                arr.ndim, ctypes.byref(out))
        if n > 0:
            return _take_buf(l, out, n)
    import struct, zlib

    crc = zlib.crc32(payload) & 0xFFFFFFFF
    return (struct.pack("<IBBH", _TF_MAGIC, code, arr.ndim, 0)
            + struct.pack("<%dq" % arr.ndim, *arr.shape)
            + struct.pack("<QI", len(payload), crc) + payload)


def tensor_unframe(buf: bytes):
    """Inverse of tensor_frame -> numpy array; raises on corruption."""
    import numpy as np

    l = lib()
    if l is not None:
        code = ctypes.c_int()
        ndim = ctypes.c_int()
        shape = (ctypes.c_int64 * 16)()
        out = ctypes.POINTER(ctypes.c_char)()
        n = l.ptpu_tensor_unframe(buf, len(buf), ctypes.byref(code), shape,
                                  ctypes.byref(ndim), ctypes.byref(out))
        if n < 0:
            raise ValueError("bad tensor frame (code %d: magic/ndim/crc)" % n)
        data = _take_buf(l, out, n)
        shp = tuple(shape[i] for i in range(ndim.value))
        return np.frombuffer(
            data, dtype=np.dtype(_DTYPE_CODES[code.value])).reshape(shp)
    import struct, zlib

    if len(buf) < 20:
        raise ValueError("bad tensor frame: truncated")
    magic, code, ndim, _ = struct.unpack("<IBBH", buf[:8])
    if magic != _TF_MAGIC:
        raise ValueError("bad tensor frame: magic")
    if ndim > _TF_MAX_NDIM or code >= len(_DTYPE_CODES):
        raise ValueError("bad tensor frame: ndim/dtype")
    off = 8 + 8 * ndim
    if len(buf) < off + 12:
        raise ValueError("bad tensor frame: truncated header")
    shp = struct.unpack_from("<%dq" % ndim, buf, 8)
    plen, crc = struct.unpack_from("<QI", buf, off)
    if plen > len(buf) - off - 12:
        raise ValueError("bad tensor frame: truncated payload")
    payload = buf[off + 12: off + 12 + plen]
    if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        raise ValueError("bad tensor frame: CRC mismatch")
    import numpy as np

    return np.frombuffer(
        payload, dtype=np.dtype(_DTYPE_CODES[code])).reshape(shp)


# ---------------------------------------------------------------------------
# staging arena: buddy-allocator-backed host buffers for the feed path
# ---------------------------------------------------------------------------


class StagingArena:
    """Host staging pool for feed batches backed by the C++ buddy allocator
    (allocator.cc, buddy_allocator.h C19 parity). PyReader's double-buffer
    thread copies each batch into an arena-owned aligned buffer before
    jax.device_put, so the per-batch numpy heap churn disappears and H2D
    transfers read from stable, reused memory. Two rotating slots per
    (key, shape, dtype) keep the previous batch's buffer alive while its
    async copy completes (double-buffer depth 1). Degrades to plain numpy
    copies when the native library is unavailable."""

    def __init__(self, total_bytes=256 << 20, min_chunk_bytes=4096):
        self._lib = lib()
        self._h = None
        if self._lib is not None:
            self._h = self._lib.ptpu_allocator_create(total_bytes,
                                                      min_chunk_bytes)
        self._slots = {}
        self._flip = {}
        self._lock = threading.Lock()

    def stage(self, key, arr):
        """Copy `arr` into the arena; returns a numpy view over arena
        memory (or a plain copy without the native lib)."""
        import numpy as np

        arr = np.ascontiguousarray(arr)
        if self._h is None:
            return arr.copy()
        k = (key, arr.shape, arr.dtype.str)
        with self._lock:
            pair = self._slots.get(k)
            if pair is None:
                # evict this feed key's stale shapes, keeping the most
                # recent one as a spare (bucketed batches alternate a few
                # shapes; unbounded retention would pin the arena until
                # staging silently degraded to plain copies)
                stale = [k2 for k2 in self._slots
                         if k2[0] == key and k2 != k]
                for k2 in stale[:-1]:
                    self._release_slot(k2)
                stale = stale[-1:]

                def try_alloc():
                    ptrs, views = [], []
                    for _ in range(2):
                        ptr = self._lib.ptpu_alloc(self._h,
                                                   max(arr.nbytes, 1))
                        if not ptr:
                            for p in ptrs:
                                self._lib.ptpu_free(self._h, p)
                            return None
                        raw = (ctypes.c_char
                               * max(arr.nbytes, 1)).from_address(ptr)
                        views.append(np.frombuffer(
                            raw, dtype=arr.dtype).reshape(arr.shape))
                        ptrs.append(ptr)
                    return [views, ptrs, [None, None]]

                pair = try_alloc()
                if pair is None and stale:
                    # arena full: drop the spare too and retry once
                    self._release_slot(stale[0])
                    pair = try_alloc()
                if pair is None:
                    return arr.copy()
                self._slots[k] = pair
                self._flip[k] = 0
            i = self._flip[k]
            self._flip[k] = 1 - i
        views, _, pending = pair
        # the slot's previous batch may still be mid H2D copy (device_put
        # is async; PJRT reads the host buffer until the transfer lands):
        # wait for it before overwriting the arena memory
        if pending[i] is not None:
            try:
                pending[i].block_until_ready()
            except Exception:
                pass
            pending[i] = None
        view = views[i]
        view[...] = arr
        self._last_slot = (k, i)
        return view

    def note_transfer(self, staged_view, device_array):
        """Record the async device_put reading `staged_view`, so the slot
        is not overwritten until that transfer completes."""
        ks = getattr(self, "_last_slot", None)
        if ks is None:
            return
        k, i = ks
        pair = self._slots.get(k)
        if pair is not None and pair[0][i] is staged_view:
            pair[2][i] = device_array

    def _release_slot(self, k):
        """Free one slot pair (caller holds the lock): wait out in-flight
        transfers, then return the buffers to the buddy arena."""
        pair = self._slots.pop(k, None)
        self._flip.pop(k, None)
        if pair is None:
            return
        for dev in pair[2]:
            if dev is not None:
                try:
                    dev.block_until_ready()
                except Exception:
                    pass
        for p in pair[1]:
            self._lib.ptpu_free(self._h, p)

    def stats(self):
        if self._h is None:
            return {"in_use": 0, "peak": 0, "allocs": 0, "native": False}
        return {"in_use": int(self._lib.ptpu_allocator_in_use(self._h)),
                "peak": int(self._lib.ptpu_allocator_peak(self._h)),
                "allocs": int(self._lib.ptpu_allocator_alloc_count(self._h)),
                "native": True}

    def close(self):
        if self._h is not None:
            with self._lock:
                # drain in-flight transfers BEFORE freeing their host
                # buffers (PJRT reads them until the H2D copy lands),
                # then drop the views and the arena
                for k in list(self._slots):
                    self._release_slot(k)
            self._lib.ptpu_allocator_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
