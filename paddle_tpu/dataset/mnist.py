"""MNIST reader creators (parity: python/paddle/dataset/mnist.py — train()
:113, test() :121; samples are (784 float32 in [-1,1], int64 label)).
Synthetic: class-conditional Gaussian digits, deterministic by seed."""

import numpy as np

TRAIN_SIZE = 8192
TEST_SIZE = 1024


def _reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        protos = rng.normal(size=(10, 784)).astype(np.float32)
        for _ in range(n):
            label = int(rng.randint(0, 10))
            img = protos[label] + 0.3 * rng.normal(size=784).astype(
                np.float32)
            yield np.clip(img, -1.0, 1.0).astype(np.float32), label
    return reader


def train():
    return _reader(TRAIN_SIZE, seed=90051)


def test():
    return _reader(TEST_SIZE, seed=90052)
