"""WMT14 en-fr reader creators (parity: python/paddle/dataset/wmt14.py —
train()/test() yield (src_ids, trg_ids, trg_next_ids) with <s>=0, <e>=1,
<unk>=2). Synthetic, same id conventions as wmt16."""

import numpy as np

TRAIN_SIZE = 1024
TEST_SIZE = 128


def _reader(n, dict_size, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            L = int(rng.randint(4, 30))
            src = rng.randint(3, dict_size, size=L).astype(np.int64)
            trg_core = (src[::-1] % (dict_size - 3)) + 3
            trg = np.concatenate([[0], trg_core]).astype(np.int64)
            trg_next = np.concatenate([trg_core, [1]]).astype(np.int64)
            yield src.tolist(), trg.tolist(), trg_next.tolist()
    return reader


def train(dict_size=30000):
    return _reader(TRAIN_SIZE, dict_size, seed=52001)


def test(dict_size=30000):
    return _reader(TEST_SIZE, dict_size, seed=52002)


def get_dict(dict_size=30000, reverse=False):
    src = {("s%d" % i): i for i in range(dict_size)}
    trg = {("t%d" % i): i for i in range(dict_size)}
    if reverse:
        src = {v: k for k, v in src.items()}
        trg = {v: k for k, v in trg.items()}
    return src, trg
