"""MovieLens-1M reader creators (parity: python/paddle/dataset/movielens.py
— train()/test() yield [user_id, gender_id, age_id, job_id, movie_id,
category_ids, title_ids, score]; max_user_id/max_movie_id/max_job_id
helpers). Synthetic, deterministic by seed."""

import numpy as np

_MAX_USER = 6040
_MAX_MOVIE = 3952
_MAX_JOB = 20
_NUM_CATEGORIES = 18
_TITLE_VOCAB = 5174
TRAIN_SIZE = 4096
TEST_SIZE = 512

age_table = [1, 18, 25, 35, 45, 50, 56]


def max_user_id():
    return _MAX_USER


def max_movie_id():
    return _MAX_MOVIE


def max_job_id():
    return _MAX_JOB


def max_age_index():
    return len(age_table) - 1


def categories():
    return ["cat%d" % i for i in range(_NUM_CATEGORIES)]


def user_info():
    return {}


def movie_info():
    return {}


def _reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            user = int(rng.randint(1, _MAX_USER + 1))
            gender = int(rng.randint(0, 2))
            age = int(rng.randint(0, len(age_table)))
            job = int(rng.randint(0, _MAX_JOB + 1))
            movie = int(rng.randint(1, _MAX_MOVIE + 1))
            ncat = int(rng.randint(1, 4))
            cats = rng.choice(_NUM_CATEGORIES, size=ncat,
                              replace=False).astype(np.int64)
            tlen = int(rng.randint(1, 6))
            title = rng.randint(0, _TITLE_VOCAB, size=tlen).astype(np.int64)
            # score correlated with (user+movie) parity so models can learn
            base = 3.0 + ((user + movie) % 3 - 1)
            score = float(np.clip(base + rng.normal(0, 0.5), 1.0, 5.0))
            yield [user, gender, age, job, movie, cats.tolist(),
                   title.tolist(), score]
    return reader


def train():
    return _reader(TRAIN_SIZE, seed=61001)


def test():
    return _reader(TEST_SIZE, seed=61002)
