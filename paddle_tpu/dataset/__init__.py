"""Datasets (parity: python/paddle/dataset/ — mnist, cifar, uci_housing,
imdb, wmt16, movielens…).

The reference downloads real corpora at import time; this environment has
zero egress, so each dataset is a *deterministic synthetic generator* with
the exact sample shapes/dtypes/vocab structure of the original (seeded, so
train/test splits are reproducible). The reader-creator API is identical:
`dataset.mnist.train()` returns a reader function yielding samples.
"""

from . import mnist  # noqa: F401
from . import cifar  # noqa: F401
from . import uci_housing  # noqa: F401
from . import imdb  # noqa: F401
from . import wmt14  # noqa: F401
from . import wmt16  # noqa: F401
from . import movielens  # noqa: F401
from . import conll05  # noqa: F401
from . import sentiment  # noqa: F401
from . import flowers  # noqa: F401
from . import image  # noqa: F401
