"""WMT16 translation reader creators (parity: python/paddle/dataset/
wmt16.py — (src_ids, trg_ids, trg_next_ids) triples with BOS=0/EOS=1/UNK=2)."""

import numpy as np

TRAIN_SIZE = 1024
TEST_SIZE = 128


def _reader(n, src_dict_size, trg_dict_size, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            L = int(rng.randint(4, 30))
            src = rng.randint(3, src_dict_size, size=L).astype(np.int64)
            # synthetic "translation": reversed ids mapped into trg vocab
            trg_core = (src[::-1] % (trg_dict_size - 3)) + 3
            trg = np.concatenate([[0], trg_core]).astype(np.int64)
            trg_next = np.concatenate([trg_core, [1]]).astype(np.int64)
            yield src.tolist(), trg.tolist(), trg_next.tolist()
    return reader


def train(src_dict_size=10000, trg_dict_size=10000, src_lang="en"):
    return _reader(TRAIN_SIZE, src_dict_size, trg_dict_size, seed=51001)


def test(src_dict_size=10000, trg_dict_size=10000, src_lang="en"):
    return _reader(TEST_SIZE, src_dict_size, trg_dict_size, seed=51002)
