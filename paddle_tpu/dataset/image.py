"""Image preprocessing utilities (parity: python/paddle/dataset/image.py —
resize_short, to_chw, center_crop, random_crop, left_right_flip,
simple_transform, load_and_transform). Pure-numpy implementations (the
reference shells out to cv2; zero-egress image has no cv2 — bilinear resize
is implemented directly)."""

import numpy as np

__all__ = ["resize_short", "to_chw", "center_crop", "random_crop",
           "left_right_flip", "simple_transform", "load_image",
           "load_and_transform", "batch_images_from_tar"]


def _resize(im, h, w):
    """Bilinear resize of an HWC (or HW) uint8/float array."""
    src_h, src_w = im.shape[:2]
    if (src_h, src_w) == (h, w):
        return im
    ys = (np.arange(h) + 0.5) * src_h / h - 0.5
    xs = (np.arange(w) + 0.5) * src_w / w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, src_h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, src_w - 1)
    y1 = np.clip(y0 + 1, 0, src_h - 1)
    x1 = np.clip(x0 + 1, 0, src_w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0)[:, None]
    wx = np.clip(xs - x0, 0.0, 1.0)[None, :]
    was_2d = im.ndim == 2
    if was_2d:
        im = im[:, :, None]
    im_f = im.astype(np.float32)
    top = im_f[y0][:, x0] * (1 - wx[..., None]) + im_f[y0][:, x1] * wx[..., None]
    bot = im_f[y1][:, x0] * (1 - wx[..., None]) + im_f[y1][:, x1] * wx[..., None]
    out = top * (1 - wy[..., None]) + bot * wy[..., None]
    if was_2d:
        out = out[:, :, 0]
    if im.dtype != np.float32:
        out = np.round(out).astype(im.dtype)
    return out


def resize_short(im, size):
    """Resize so the shorter edge equals `size`, keeping aspect ratio."""
    h, w = im.shape[:2]
    if h < w:
        return _resize(im, size, int(round(w * size / h)))
    return _resize(im, int(round(h * size / w)), size)


def to_chw(im, order=(2, 0, 1)):
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h0 = max((h - size) // 2, 0)
    w0 = max((w - size) // 2, 0)
    return im[h0:h0 + size, w0:w0 + size]


def random_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h0 = np.random.randint(0, max(h - size, 0) + 1)
    w0 = np.random.randint(0, max(w - size, 0) + 1)
    return im[h0:h0 + size, w0:w0 + size]


def left_right_flip(im, is_color=True):
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None):
    """resize_short → crop (random+flip when training, center otherwise) →
    CHW float32, optionally mean-subtracted (parity: image.py
    simple_transform)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size)
        if np.random.randint(2) == 0:
            im = left_right_flip(im)
    else:
        im = center_crop(im, crop_size)
    if im.ndim == 2:
        im = im[:, :, None]
    im = to_chw(im).astype(np.float32)
    if mean is not None:
        mean = np.asarray(mean, np.float32)
        im -= mean[:, None, None] if mean.ndim == 1 else mean
    return im


def load_image(file_path, is_color=True):
    """Load an image file saved as .npy (the zero-egress stand-in for
    cv2.imread)."""
    return np.load(file_path)


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)


def batch_images_from_tar(data_file, dataset_name, img2label,
                          num_per_batch=1024):
    raise NotImplementedError(
        "tar batching requires on-disk corpora; use the synthetic dataset "
        "readers (paddle_tpu.dataset.*) in this environment")
