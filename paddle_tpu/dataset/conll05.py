"""CoNLL-2005 SRL reader creators (parity: python/paddle/dataset/conll05.py
— test() yields (word, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, verb, mark,
label) id sequences; get_dict()/get_embedding() helpers). Synthetic."""

import numpy as np

_WORD_VOCAB = 44068
_VERB_VOCAB = 3162
_LABEL_VOCAB = 59
TEST_SIZE = 512


def get_dict():
    word_dict = {("w%d" % i): i for i in range(_WORD_VOCAB)}
    verb_dict = {("v%d" % i): i for i in range(_VERB_VOCAB)}
    label_dict = {("l%d" % i): i for i in range(_LABEL_VOCAB)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    rng = np.random.RandomState(71000)
    return rng.normal(scale=0.1,
                      size=(_WORD_VOCAB, 32)).astype(np.float32)


def _reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            L = int(rng.randint(4, 40))
            words = rng.randint(0, _WORD_VOCAB, size=L).astype(np.int64)
            # the five context windows are shifts of the word sequence
            ctxs = [np.roll(words, s) for s in (2, 1, 0, -1, -2)]
            verb_idx = int(rng.randint(0, L))
            verb = np.full(L, rng.randint(0, _VERB_VOCAB), np.int64)
            mark = np.zeros(L, np.int64)
            mark[verb_idx] = 1
            labels = rng.randint(0, _LABEL_VOCAB, size=L).astype(np.int64)
            yield tuple(x.tolist() for x in
                        [words] + ctxs + [verb, mark, labels])
    return reader


def test():
    return _reader(TEST_SIZE, seed=71002)
