"""IMDB sentiment reader creators (parity: python/paddle/dataset/imdb.py —
word-id sequences + binary label; word_dict() vocabulary)."""

import numpy as np

_VOCAB = 5149  # reference vocab size ballpark
TRAIN_SIZE = 2048
TEST_SIZE = 256


def word_dict():
    return {("w%d" % i).encode(): i for i in range(_VOCAB)}


def _reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            label = int(rng.randint(0, 2))
            length = int(rng.randint(8, 120))
            lo, hi = (_VOCAB // 2, _VOCAB) if label else (2, _VOCAB // 2)
            words = rng.randint(lo, hi, size=length).astype(np.int64)
            yield words.tolist(), label
    return reader


def train(word_idx=None):
    return _reader(TRAIN_SIZE, seed=41001)


def test(word_idx=None):
    return _reader(TEST_SIZE, seed=41002)
