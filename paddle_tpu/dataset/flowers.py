"""Oxford-102 flowers reader creators (parity: python/paddle/dataset/
flowers.py — train()/test()/valid() yield (3x224x224 float32 CHW image,
int64 label in [0,102))). Synthetic class-conditional color fields."""

import numpy as np

_CLASSES = 102
TRAIN_SIZE = 1024
TEST_SIZE = 128
VALID_SIZE = 128


def _reader(n, seed):
    def reader():
        # label->color mapping shared by all splits (fixed seed) so a model
        # trained on train() is actually evaluable on test()/valid()
        means = np.random.RandomState(31000).uniform(
            -0.5, 0.5, size=(_CLASSES, 3)).astype(np.float32)
        rng = np.random.RandomState(seed)
        for _ in range(n):
            label = int(rng.randint(0, _CLASSES))
            img = (means[label][:, None, None]
                   + 0.2 * rng.normal(size=(3, 224, 224))).astype(np.float32)
            yield img, label
    return reader


def train(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _reader(TRAIN_SIZE, seed=31001)


def test(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _reader(TEST_SIZE, seed=31002)


def valid(mapper=None, buffered_size=1024, use_xmap=True):
    return _reader(VALID_SIZE, seed=31003)
