"""CIFAR reader creators (parity: python/paddle/dataset/cifar.py —
train10/test10/train100/test100; samples are (3072 float32, int label))."""

import numpy as np

TRAIN_SIZE = 4096
TEST_SIZE = 512


def _reader(n, num_classes, seed):
    def reader():
        rng = np.random.RandomState(seed)
        protos = rng.normal(size=(num_classes, 3 * 32 * 32)).astype(
            np.float32)
        for _ in range(n):
            label = int(rng.randint(0, num_classes))
            img = protos[label] + 0.25 * rng.normal(
                size=3 * 32 * 32).astype(np.float32)
            yield img.astype(np.float32), label
    return reader


def train10():
    return _reader(TRAIN_SIZE, 10, seed=20061)


def test10():
    return _reader(TEST_SIZE, 10, seed=20062)


def train100():
    return _reader(TRAIN_SIZE, 100, seed=20063)


def test100():
    return _reader(TEST_SIZE, 100, seed=20064)
