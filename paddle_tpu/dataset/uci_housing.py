"""UCI housing reader creators (parity: python/paddle/dataset/uci_housing.py
— 13 float features, float target; used by fit-a-line)."""

import numpy as np

TRAIN_SIZE = 404
TEST_SIZE = 102
_W = None


def _true_w(rng):
    global _W
    if _W is None:
        _W = rng.uniform(-2, 2, size=(13,)).astype(np.float32)
    return _W


def _reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        w = _true_w(np.random.RandomState(13))
        for _ in range(n):
            x = rng.uniform(-1, 1, size=13).astype(np.float32)
            y = np.array([x @ w + 0.5 + 0.05 * rng.normal()], np.float32)
            yield x, y
    return reader


def train():
    return _reader(TRAIN_SIZE, seed=31001)


def test():
    return _reader(TEST_SIZE, seed=31002)
