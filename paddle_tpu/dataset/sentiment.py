"""NLTK movie-review sentiment reader creators (parity:
python/paddle/dataset/sentiment.py — train()/test() yield (word-id list,
label in {0,1}); get_word_dict()). Synthetic, label-correlated vocab."""

import numpy as np

_VOCAB = 2048
NUM_TRAINING_INSTANCES = 1600
NUM_TOTAL_INSTANCES = 2000


def get_word_dict():
    return {("w%d" % i): i for i in range(_VOCAB)}


def _reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            label = int(rng.randint(0, 2))
            length = int(rng.randint(8, 80))
            lo, hi = (_VOCAB // 2, _VOCAB) if label else (0, _VOCAB // 2)
            words = rng.randint(lo, hi, size=length).astype(np.int64)
            yield words.tolist(), label
    return reader


def train():
    return _reader(NUM_TRAINING_INSTANCES, seed=81001)


def test():
    return _reader(NUM_TOTAL_INSTANCES - NUM_TRAINING_INSTANCES, seed=81002)
