"""Model save/load (parity: python/paddle/fluid/io.py — save_vars :98,
save_params :232, save_persistables :460, load_* :510-693,
save_inference_model :898, load_inference_model :1074; kernels
operators/save_op.cc:25 / load_op.cc).

Format: one `.npz`-style directory (or single combined file) of named numpy
arrays + a JSON program for inference export. Orbax-grade sharded
checkpointing for the distributed path lives in parallel/checkpoint.py.
"""

import json
import os

import numpy as np

from . import framework
from .core.scope import global_scope
from .framework import Program

from .reader import PyReader  # noqa: F401  (parity: fluid.io.PyReader)

__all__ = [
    "save_vars", "save_params", "save_persistables",
    "load_vars", "load_params", "load_persistables",
    "save_inference_model", "load_inference_model", "save_train_model",
    "get_program_parameter", "get_program_persistable_vars",
    "PyReader",
]


def _is_persistable(var):
    return var.persistable and not var.is_data


def _is_parameter(var):
    return isinstance(var, framework.Parameter)


def get_program_parameter(program):
    return [v for v in program.global_block().vars.values() if _is_parameter(v)]


def get_program_persistable_vars(program):
    return [v for v in program.list_vars() if _is_persistable(v)]


def _gather(scope, var_list):
    out = {}
    for v in var_list:
        val = scope.get(v.name)
        if val is None:
            raise RuntimeError("var %r has no value in scope; run startup "
                               "program before saving" % v.name)
        out[v.name] = np.asarray(val)
    return out


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    main_program = main_program or framework.default_main_program()
    if vars is None:
        vars = [v for v in main_program.list_vars()
                if predicate is None or predicate(v)]
    scope = global_scope()
    arrays = _gather(scope, vars)
    os.makedirs(dirname, exist_ok=True)
    if filename is not None:
        np.savez(os.path.join(dirname, filename), **arrays)
    else:
        for name, arr in arrays.items():
            np.save(os.path.join(dirname, name.replace("/", "__")), arr)


def save_params(executor, dirname, main_program=None, filename=None):
    main_program = main_program or framework.default_main_program()
    save_vars(executor, dirname, main_program,
              vars=get_program_parameter(main_program), filename=filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    main_program = main_program or framework.default_main_program()
    save_vars(executor, dirname, main_program,
              vars=get_program_persistable_vars(main_program),
              filename=filename)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    main_program = main_program or framework.default_main_program()
    if vars is None:
        vars = [v for v in main_program.list_vars()
                if predicate is None or predicate(v)]
    scope = global_scope()
    if filename is not None:
        path = os.path.join(dirname, filename)
        if not os.path.exists(path) and os.path.exists(path + ".npz"):
            path += ".npz"  # np.savez appends the suffix on save
        with np.load(path) as data:
            for v in vars:
                if v.name in data:
                    scope.set(v.name, data[v.name])
                else:
                    raise RuntimeError("var %r missing in %s" % (v.name, filename))
    else:
        for v in vars:
            path = os.path.join(dirname, v.name.replace("/", "__") + ".npy")
            if not os.path.exists(path):
                raise RuntimeError("no saved file for var %r at %s"
                                   % (v.name, path))
            scope.set(v.name, np.load(path))


def load_params(executor, dirname, main_program=None, filename=None):
    main_program = main_program or framework.default_main_program()
    load_vars(executor, dirname, main_program,
              vars=get_program_parameter(main_program), filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    main_program = main_program or framework.default_main_program()
    load_vars(executor, dirname, main_program,
              vars=get_program_persistable_vars(main_program),
              filename=filename)


def _prune_program(program, feed_names, fetch_vars):
    """Prune to the subgraph producing fetch_vars from feed_names (parity:
    Program._prune used by save_inference_model)."""
    block = program.global_block()
    needed = set(v.name for v in fetch_vars)
    keep = [False] * len(block.ops)
    for i in reversed(range(len(block.ops))):
        op = block.ops[i]
        if any(n in needed for n in op.output_names()):
            keep[i] = True
            for n in op.input_names():
                needed.add(n)
    pruned = program.clone(for_test=True)
    pb = pruned.global_block()
    pb.ops = [op for i, op in enumerate(pb.ops) if keep[i]]
    return pruned


def _write_sealed_model(dirname, program, feed_names, fetch_names,
                        model_filename=None, params_filename=None,
                        param_vars=None):
    """Shared exporter tail: write the sealed __model__ frame (magic + format
    version + CRC — framework/version.h IsProgramVersionSupported parity, via
    the native layer) and, when param_vars is not None, the __params__ savez
    of their scope values."""
    from .core import native

    os.makedirs(dirname, exist_ok=True)
    meta = {
        "program": json.loads(program.to_json()),
        "feed_names": list(feed_names),
        "fetch_names": list(fetch_names),
    }
    model_path = os.path.join(dirname, model_filename or "__model__")
    with open(model_path, "wb") as f:
        f.write(native.program_seal(json.dumps(meta).encode("utf-8")))
    if param_vars is not None:
        arrays = _gather(global_scope(), param_vars)
        np.savez(os.path.join(dirname, params_filename or "__params__"),
                 **arrays)


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True,
                         program_only=False):
    main_program = main_program or framework.default_main_program()
    pruned = _prune_program(main_program, feeded_var_names, target_vars)
    fetch_names = [v.name for v in target_vars]
    params = None
    if not program_only:
        params = [v for v in pruned.list_vars() if _is_persistable(v)]
        # only persistables actually referenced by the pruned op list
        used = set()
        for op in pruned.global_block().ops:
            used.update(op.input_names())
            used.update(op.output_names())
        params = [v for v in params if v.name in used]
    _write_sealed_model(dirname, pruned, feeded_var_names, fetch_names,
                        model_filename, params_filename, params)
    return fetch_names


def save_train_model(dirname, feeded_var_names, target_vars, executor,
                     main_program=None):
    """Export the FULL training program (backward + optimizer ops included,
    no pruning) plus every persistable, in the sealed __model__/__params__
    format load_inference_model reads. This is the artifact the pure-C++
    trainer consumes (parity: paddle/fluid/train/demo_trainer.cc, which
    trains from a saved ProgramDesc + persistables)."""
    main_program = main_program or framework.default_main_program()
    params = [v for v in main_program.list_vars() if _is_persistable(v)]
    _write_sealed_model(dirname, main_program, feeded_var_names,
                        [v.name for v in target_vars], param_vars=params)
    return [v.name for v in target_vars]


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None, pserver_endpoints=None,
                         reference_format=None):
    """reference_format: True forces parsing `__model__` as the
    reference's framework.proto ProgramDesc binary (+ save/save_combine
    LoDTensor param files); False forces this package's sealed-JSON
    format; None (default) sniffs the bytes (reference_format.py)."""
    from .core import native, serde

    model_path = os.path.join(dirname, model_filename or "__model__")
    with open(model_path, "rb") as f:
        raw = f.read()
    if reference_format is None:
        from .reference_format import is_reference_program_bytes

        reference_format = is_reference_program_bytes(raw)
    if reference_format:
        from . import reference_format as refmt

        program, feed_names, fetch_names = \
            refmt.program_from_reference_bytes(raw)
        refmt.load_reference_persistables(dirname, program,
                                          filename=params_filename)
        fetch_vars = [program.global_block().var(n) for n in fetch_names]
        return [program, feed_names, fetch_vars]
    try:
        meta = json.loads(native.program_unseal(raw).decode("utf-8"))
    except ValueError:
        meta = json.loads(raw.decode("utf-8"))  # pre-seal format
    program = serde.program_from_desc(meta["program"])
    params_path = os.path.join(dirname, params_filename or "__params__")
    if not params_path.endswith(".npz"):
        params_path += ".npz"
    if os.path.exists(params_path):
        scope = global_scope()
        with np.load(params_path) as data:
            for name in data.files:
                scope.set(name, data[name])
    feed_names = meta["feed_names"]
    fetch_vars = [program.global_block().var(n) for n in meta["fetch_names"]]
    return [program, feed_names, fetch_vars]
