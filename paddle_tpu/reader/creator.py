"""Simple reader creators (parity: python/paddle/reader/creator.py —
np_array, text_file, recordio)."""

__all__ = ["np_array", "text_file", "recordio"]


def np_array(x):
    """Reader over the rows (highest-dimension slices) of a numpy array."""

    def reader():
        if x.ndim < 1:
            yield x
            return
        for e in x:
            yield e

    return reader


def text_file(path):
    """Reader yielding the file's lines with the trailing newline
    stripped."""

    def reader():
        with open(path, "r") as f:
            for line in f:
                yield line.rstrip("\n")

    return reader


def recordio(paths, buf_size=100):
    """Reader over recordio shard file(s) — deserialized samples (the
    reference's cloudpickle records; here the recordio bridge's encoding,
    see recordio_writer.py)."""
    from ..recordio_writer import recordio_reader_creator

    if isinstance(paths, str):
        paths = paths.split(",")
    return recordio_reader_creator(list(paths))
