"""Composable reader decorators + PyReader (parity:
python/paddle/reader/decorator.py:36-360 — map_readers, buffered, compose,
chain, shuffle, firstn, xmap_readers, cache; python/paddle/fluid/reader.py
PyReader; C++ side operators/reader/ C17).

A "reader" is a nullary callable returning an iterator of samples, exactly
as in the reference. The double-buffered host->HBM feed (BufferedReader
parity) lives in `paddle_tpu.reader.pipeline.DeviceFeeder`.
"""

import itertools
import queue as _queue
import random as _random
import threading
import time as _time

from ..observability import metrics as _obs_metrics

__all__ = ["map_readers", "buffered", "compose", "chain", "shuffle",
           "firstn", "xmap_readers", "cache", "batch", "PyReader",
           "multiprocess_reader", "PipeReader", "creator", "Fake"]


class Fake:
    """Cache the first sample of a real reader and replay it `data_num`
    times — for feed-pipeline speed testing without parsing cost (parity:
    python/paddle/reader/decorator.py:531 Fake)."""

    def __init__(self):
        self.data = None
        self.yield_num = 0

    def __call__(self, reader, data_num):
        def fake_reader():
            if self.data is None:
                self.data = next(reader())
            while self.yield_num < data_num:
                yield self.data
                self.yield_num += 1
            self.yield_num = 0

        return fake_reader

from . import creator  # noqa: F401,E402


def map_readers(func, *readers):
    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            _random.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def chain(*readers):
    def reader():
        rs = [r() for r in readers]
        for e in itertools.chain(*rs):
            yield e

    return reader


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, **kwargs):
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        if isinstance(x, tuple):
            return x
        return (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(list(map(make_tuple, outputs)), ())
        else:
            sentinel = object()
            import itertools

            for outputs in itertools.zip_longest(*rs, fillvalue=sentinel):
                if sentinel in outputs:
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned — one reader "
                        "ended before the others")
                yield sum(list(map(make_tuple, outputs)), ())

    return reader


def buffered(reader, size):
    """Background-thread prefetch buffer (decorator.py buffered)."""

    class EndSignal:
        pass

    end = EndSignal()

    def read_worker(r, q):
        for d in r:
            q.put(d)
        q.put(end)

    def data_reader():
        r = reader()
        q = _queue.Queue(maxsize=size)
        t = threading.Thread(target=read_worker, args=(r, q),
                             name="ptpu-reader-buffered")
        t.daemon = True
        t.start()
        e = q.get()
        while not isinstance(e, EndSignal):
            yield e
            e = q.get()

    return data_reader


def firstn(reader, n):
    def data_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return data_reader


def cache(reader):
    all_data = tuple(reader())

    def data_reader():
        for d in all_data:
            yield d

    return data_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader with worker threads (decorator.py
    xmap_readers)."""
    end = object()
    in_q = _queue.Queue(buffer_size)
    out_q = _queue.Queue(buffer_size)

    def data_reader():
        from ..analysis.concurrency import make_lock

        finished = [0]
        lock = make_lock("reader.xmap_finished")

        def read_worker():
            for d in reader():
                in_q.put(d)
            for _ in range(process_num):
                in_q.put(end)

        def map_worker():
            while True:
                d = in_q.get()
                if d is end:
                    break
                out_q.put(mapper(d))
            with lock:
                finished[0] += 1
                if finished[0] == process_num:
                    out_q.put(end)

        t = threading.Thread(target=read_worker, name="ptpu-xmap-read")
        t.daemon = True
        t.start()
        workers = []
        for i in range(process_num):
            w = threading.Thread(target=map_worker,
                                 name="ptpu-xmap-map-%d" % i)
            w.daemon = True
            w.start()
            workers.append(w)
        while True:
            d = out_q.get()
            if d is end:
                break
            yield d

    return data_reader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Thread-based fan-in (the reference uses fork+pipe; threads suffice
    for numpy-producing readers under the GIL-releasing feed path)."""
    return chain(*readers)


def batch(reader, batch_size, drop_last=False):
    """Group samples into lists of batch_size (python/paddle/batch.py)."""

    def batch_reader():
        r = reader()
        b = []
        for instance in r:
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader


class PyReader:
    """Feed-pipeline object (parity: fluid/reader.py PyReader; C++
    lod_tensor_blocking_queue.h). decorate_sample_list_generator feeds
    batches through a background thread into the executor feed."""

    def __init__(self, feed_list=None, capacity=16, use_double_buffer=True,
                 iterable=True, return_list=False, worker_restarts=0):
        self._feed_list = feed_list
        self._capacity = capacity
        self._iterable = iterable
        self._generator = None
        self._places = None
        self._feeder = None
        self._use_double_buffer = use_double_buffer
        # bounded worker-restart budget: a generator that raises is
        # re-invoked from scratch up to this many times before the
        # exception is forwarded to the consumer (docs/RESILIENCE.md)
        self._worker_restarts = int(worker_restarts)
        self._stage_warned = False
        # buddy-allocator staging pool (native/allocator.cc, C19): batches
        # are copied into arena-backed buffers before the async device_put
        self._arena = None
        # optional (name, value) -> jax sharding for the staged transfer
        # (set_feed_sharding; e.g. a _DataParallelStep.feed_sharding)
        self._sharding_fn = None

    def decorate_sample_list_generator(self, generator, places=None):
        from ..data_feeder import DataFeeder

        self._feeder = DataFeeder(self._feed_list)
        self._generator = generator
        self._places = places

    decorate_paddle_reader = decorate_sample_list_generator

    def set_feed_sharding(self, sharding_fn):
        """Attach a (name, value) -> sharding decision so the double
        buffer's device_put lands batches in the compiled step's target
        layout (e.g. pass a CompiledProgram step's `feed_sharding`, or
        `executor._feed_sharding`)."""
        self._sharding_fn = sharding_fn

    def decorate_sample_generator(self, sample_generator, batch_size,
                                  drop_last=True, places=None):
        """Per-SAMPLE generator source (parity: fluid/reader.py
        decorate_sample_generator): batches are assembled host-side then
        fed like decorate_sample_list_generator."""
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")

        def batched():
            buf = []
            for sample in sample_generator():
                buf.append(sample)
                if len(buf) == batch_size:
                    yield buf
                    buf = []
            if buf and not drop_last:
                yield buf

        self.decorate_sample_list_generator(batched, places)

    def decorate_batch_generator(self, generator, places=None):
        self._generator = generator
        self._feeder = None
        self._places = places

    def __call__(self):
        return self.__iter__()

    def __iter__(self):
        if self._generator is None:
            raise RuntimeError("PyReader has no decorated generator")
        q = _queue.Queue(self._capacity)
        end = object()

        class _WorkerFailure:
            """Sentinel carrying the worker's exception (with its
            original traceback) to the consumer thread — a parse error
            must raise at next(), not silently end (or hang) the
            stream."""

            def __init__(self, exc):
                self.exc = exc

        def worker():
            restarts_left = self._worker_restarts
            while True:
                try:
                    for sample_list in self._generator():
                        if self._feeder is not None:
                            q.put(self._feeder.feed(sample_list))
                        else:
                            q.put(sample_list)
                    q.put(end)
                    return
                except Exception as exc:  # forwarded to the consumer
                    if restarts_left > 0:
                        restarts_left -= 1
                        _obs_metrics.counter(
                            "reader/worker_restarts").inc()
                        import warnings

                        warnings.warn(
                            "PyReader worker raised %r — restarting "
                            "generator FROM SCRATCH (%d restarts left); "
                            "batches already delivered before the "
                            "failure will repeat" % (exc, restarts_left),
                            RuntimeWarning)
                        continue
                    q.put(_WorkerFailure(exc))
                    return

        t = threading.Thread(target=worker, name="ptpu-pyreader")
        t.daemon = True
        t.start()

        # double buffer: async-transfer the NEXT batch to device while the
        # CURRENT one trains (operators/reader/buffered_reader.cc parity —
        # H2D overlap on its own stream; jax.device_put is async)
        pending = None
        while True:
            # re-checked per batch so enable()/disable() mid-epoch takes
            # effect here just like it does in Executor.run
            rec = _obs_metrics.enabled()
            t_wait = _time.perf_counter() if rec else 0.0
            item = q.get()
            if item is end:
                break
            if isinstance(item, _WorkerFailure):
                # deliver the already-staged good batch first, then
                # re-raise in the consumer with the worker's traceback
                if pending is not None:
                    yield pending
                    pending = None
                raise item.exc
            if rec:
                # batch-wait is the starvation signal: high wait + low
                # queue depth means the host parse can't keep the device
                # fed. Recorded only for real batches — the sentinel's
                # wait measures producer teardown, not starvation.
                _obs_metrics.histogram("reader/batch_wait_time").observe(
                    _time.perf_counter() - t_wait)
                _obs_metrics.gauge("reader/queue_depth").set(q.qsize())
                _obs_metrics.counter("reader/batches").inc()
            staged = self._stage(item, depth=1 if pending is not None else 0)
            if pending is not None:
                yield pending
            pending = staged
        if pending is not None:
            yield pending

    def _stage(self, item, depth=0):
        if not self._use_double_buffer or not isinstance(item, dict):
            return item
        from ..executor import check_feed_int64

        # the int64-truncation guard is a USER error — raise it here with
        # the batch in hand rather than letting the staging fallback
        # below swallow it and the executor rediscover it a step later
        for k, v in item.items():
            check_feed_int64(k, v)
        try:
            import jax

            if isinstance(item, dict):
                if self._arena is None:
                    from ..core.native import StagingArena

                    self._arena = StagingArena()
                # copy into stable arena-owned host buffers (two rotating
                # slots per feed name), then async H2D from them — the
                # reference's pinned staging in buffered_reader.cc. The
                # arena blocks on a slot's in-flight transfer before
                # reusing its memory (note_transfer bookkeeping). With a
                # sharding fn attached (set_feed_sharding), each value
                # lands directly in the compiled step's target layout.
                sharding_fn = self._sharding_fn

                def _one(k, v):
                    staged = self._arena.stage(k, v)
                    sh = (sharding_fn(k, staged)
                          if sharding_fn is not None else None)
                    dev = (jax.device_put(staged, sh) if sh is not None
                           else jax.device_put(staged))
                    self._arena.note_transfer(staged, dev)
                    return dev

                out = {k: _one(k, v) for k, v in item.items()}
                if _obs_metrics.enabled():
                    from ..async_engine import _nbytes

                    _obs_metrics.counter("feed/h2d_bytes").inc(
                        _nbytes(out.values()))
                    _obs_metrics.gauge("feed/prefetch_depth").set(depth)
                return out
        except Exception as exc:
            # staging infrastructure failure (native arena absent, an
            # exotic value device_put rejects): fall back to the host
            # batch — the step still runs — but never silently: warn once
            # and count, so a run that quietly lost its double buffer is
            # visible in the metrics dump
            _obs_metrics.counter("reader/stage_fallbacks").inc()
            if not self._stage_warned:
                self._stage_warned = True
                import warnings

                warnings.warn(
                    "PyReader double-buffer staging failed (%r); feeding "
                    "host batches directly" % (exc,), RuntimeWarning)
        return item

    def staging_stats(self):
        """Buddy-allocator stats for the staging arena (get_mem_usage
        parity): {'in_use', 'peak', 'allocs', 'native'}."""
        if self._arena is None:
            return {"in_use": 0, "peak": 0, "allocs": 0, "native": False}
        return self._arena.stats()

    def start(self):
        self._iter = iter(self)

    def reset(self):
        self._iter = None

    def next(self):
        return next(self._iter)


class PipeReader:
    """Stream records from a shell command's stdout (parity:
    python/paddle/reader/decorator.py PipeReader — reads the process output
    in chunks and yields lines; used to read from hadoop/gzip pipes)."""

    def __init__(self, command, bufsize=8192, file_type="plain"):
        if not isinstance(command, str):
            raise TypeError("PipeReader command must be a string")
        self.command = command
        self.bufsize = bufsize
        self.file_type = file_type

    def get_line(self, cut_lines=True, line_break="\n"):
        import codecs
        import subprocess

        proc = subprocess.Popen(
            self.command.split(" "), bufsize=self.bufsize,
            stdout=subprocess.PIPE)
        if self.file_type == "gzip":
            import zlib

            decomp = zlib.decompressobj(32 + zlib.MAX_WBITS)
        # incremental decoder: a multibyte char may straddle a chunk boundary
        decoder = codecs.getincrementaldecoder("utf-8")()
        remained = ""
        while True:
            buff = proc.stdout.read(self.bufsize)
            if not buff:
                break
            if self.file_type == "gzip":
                raw = decomp.decompress(buff)
                # multi-member gzip (concatenated part files): restart the
                # decompressor on the leftover bytes of each finished member
                while decomp.eof and decomp.unused_data:
                    tail = decomp.unused_data
                    decomp = zlib.decompressobj(32 + zlib.MAX_WBITS)
                    raw += decomp.decompress(tail)
                decomp_buff = decoder.decode(raw)
            else:
                decomp_buff = decoder.decode(buff)
            if cut_lines:
                lines = (remained + decomp_buff).split(line_break)
                remained = lines.pop(-1)
                for line in lines:
                    yield line
            else:
                yield decomp_buff
        tail = decoder.decode(
            decomp.flush() if self.file_type == "gzip" else b"", final=True)
        if cut_lines:
            remained += tail
        elif tail:
            yield tail
        if remained:
            yield remained
        returncode = proc.wait()
        if returncode != 0:
            raise RuntimeError(
                "PipeReader command %r exited with %d"
                % (self.command, returncode))
