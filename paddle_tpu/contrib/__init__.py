"""Contrib toolkit (parity: python/paddle/fluid/contrib/ — mixed precision,
quantization, slim, decoder, memory estimation)."""

from . import mixed_precision
from . import quantize
from . import slim
from . import decoder
from .memory_usage_calc import memory_usage
from .decoder import BeamSearchDecoder, StateCell, TrainingDecoder
from .quantize import QuantizeTranspiler
from .int8_utility import Calibrator
from .slim import Compressor
from .hdfs_utils import HDFSClient, multi_download, multi_upload

__all__ = ["mixed_precision", "quantize", "slim", "decoder", "memory_usage",
           "BeamSearchDecoder", "StateCell", "TrainingDecoder",
           "QuantizeTranspiler", "Calibrator", "Compressor", "HDFSClient",
           "multi_download", "multi_upload"]
