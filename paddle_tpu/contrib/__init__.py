"""Contrib toolkit (parity: python/paddle/fluid/contrib/ — mixed precision,
quantization, slim, decoder, memory estimation)."""

from . import mixed_precision
from . import quantize
from . import slim
from . import decoder
from . import extend_optimizer
from . import reader
from . import utils
from .memory_usage_calc import memory_usage
from .decoder import BeamSearchDecoder, InitState, StateCell, TrainingDecoder
from .extend_optimizer import extend_with_decoupled_weight_decay
from .op_frequence import op_freq_statistic
from .quantize import QuantizeTranspiler
from .int8_utility import Calibrator
from .reader import ctr_reader
from .slim import Compressor
from .hdfs_utils import HDFSClient, multi_download, multi_upload
from .utils import (convert_dist_to_sparse_program,
                    load_persistables_for_increment,
                    load_persistables_for_inference)

__all__ = ["mixed_precision", "quantize", "slim", "decoder",
           "extend_optimizer", "reader", "utils", "memory_usage",
           "BeamSearchDecoder", "InitState", "StateCell", "TrainingDecoder",
           "QuantizeTranspiler", "Calibrator", "Compressor", "HDFSClient",
           "multi_download", "multi_upload",
           "extend_with_decoupled_weight_decay", "op_freq_statistic",
           "ctr_reader", "convert_dist_to_sparse_program",
           "load_persistables_for_increment",
           "load_persistables_for_inference"]
