from .decorator import decorate, OptimizerWithMixedPrecision, \
    AutoMixedPrecisionLists

__all__ = ["decorate", "OptimizerWithMixedPrecision",
           "AutoMixedPrecisionLists"]
