"""Mixed-precision training decorator (parity: python/paddle/fluid/contrib/
mixed_precision/decorator.py:26 `OptimizerWithMixedPrecision` — loss scaling
+ master fp32 weights :127-147).

TPU-native: the low-precision compute dtype is bfloat16 (the MXU's native
input type), selected per-op by the same white/black-list discipline as the
reference's fp16 lists. Master weights stay fp32 — on TPU, params already
live in fp32 and XLA inserts the bf16 casts this pass requests via the
`cast` ops, so "master weight copies" need no duplicate storage."""

import numpy as np

from ... import framework
from ...framework import default_main_program, default_startup_program
from ...layer_helper import LayerHelper

__all__ = ["decorate", "OptimizerWithMixedPrecision", "AutoMixedPrecisionLists"]


class AutoMixedPrecisionLists:
    """White list runs in bf16, black list stays fp32 (parity:
    contrib/mixed_precision/fp16_lists.py)."""

    def __init__(self, custom_white_list=None, custom_black_list=None):
        self.white_list = {"matmul", "mul", "conv2d", "conv3d",
                           "depthwise_conv2d",
                           "flash_attention",
                           "fused_multihead_attention"} \
            | set(custom_white_list or ())
        self.black_list = {"softmax", "softmax_with_cross_entropy",
                           "cross_entropy", "cross_entropy2", "mean",
                           "layer_norm", "batch_norm",
                           "exp", "log", "sum"} | set(custom_black_list or ())
        self.white_list -= self.black_list


class OptimizerWithMixedPrecision:
    def __init__(self, optimizer, amp_lists, init_loss_scaling,
                 use_dynamic_loss_scaling, incr_every_n_steps,
                 decr_every_n_nan_or_inf, incr_ratio, decr_ratio,
                 use_bf16=True):
        self._optimizer = optimizer
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        self._init_loss_scaling = float(init_loss_scaling)
        self._use_dynamic = use_dynamic_loss_scaling
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._use_bf16 = use_bf16
        self._loss_scaling = None

    # parity surface
    def get_loss_scaling(self):
        return self._loss_scaling

    def _create_state(self, prog, startup):
        from ... import unique_name

        def mk(name, value, dtype="float32"):
            gb = prog.global_block()
            v = gb.create_var(name=name, shape=(1,), dtype=dtype,
                              persistable=True)
            sb = startup.global_block()
            if not sb.has_var(name):
                sv = sb.create_var(name=name, shape=(1,), dtype=dtype,
                                   persistable=True)
                from ...initializer import Constant

                Constant(value)(sv, sb)  # appends the fill op to startup
            return v

        # unique per decorated optimizer so two AMP optimizers in one
        # program never share scaling state
        self._loss_scaling = mk(unique_name.generate("loss_scaling"),
                                self._init_loss_scaling)
        self._good_steps = mk(unique_name.generate("good_steps"), 0.0,
                              "int32")
        self._bad_steps = mk(unique_name.generate("bad_steps"), 0.0, "int32")

    def _rewrite_bf16(self, prog):
        """Insert bf16 casts around white-list ops (fp16_utils.py
        rewrite_program parity, with bfloat16 as the compute type)."""
        if not self._use_bf16:
            return
        # prog.blocks already enumerates every control-flow sub-block
        # (recompute/while/cond bodies are created via _create_block) — a
        # matmul inside a rematerialized transformer layer gets marked too
        for block in prog.blocks:
            for op in block.ops:
                if op.type in self._amp_lists.white_list:
                    op.attrs["__amp_bf16__"] = True
        prog._bump_version()

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        prog = loss.block.program
        startup = startup_program or default_startup_program()
        self._create_state(prog, startup)
        self._rewrite_bf16(prog)
        with framework.program_guard(prog, startup):
            from ...layers import nn as nn_layers

            scaled_loss = nn_layers.elementwise_mul(loss, self._loss_scaling)
        self._scaled_loss = scaled_loss
        params_grads = self._optimizer.backward(
            scaled_loss, startup_program, parameter_list, no_grad_set,
            callbacks)
        return params_grads

    def apply_gradients(self, params_grads):
        prog = params_grads[0][0].block.program
        block = prog.global_block()
        helper = LayerHelper("amp")
        from ... import unique_name

        if not self._use_dynamic and self._init_loss_scaling == 1.0:
            # static scale of 1: unscale is the identity and nothing reads
            # FoundInfinite — bf16 has fp32's exponent range, so the
            # inf-scan pass (a full read of every gradient) buys nothing
            return self._optimizer.apply_gradients(params_grads)

        grads = [g for _, g in params_grads]
        found_inf = block.create_var(
            name=unique_name.generate("find_infinite_scale"),
            dtype="bool", shape=(1,))
        unscaled = []
        for _, g in params_grads:
            ng = block.create_var(name=g.name + "@UNSCALED", dtype=g.dtype,
                                  shape=g.shape)
            unscaled.append(ng)
        block.append_op(
            type="check_finite_and_unscale",
            inputs={"X": grads, "Scale": [self._loss_scaling]},
            outputs={"Out": unscaled, "FoundInfinite": [found_inf]})
        if self._use_dynamic:
            block.append_op(
                type="update_loss_scaling",
                inputs={"PrevLossScaling": [self._loss_scaling],
                        "InGoodSteps": [self._good_steps],
                        "InBadSteps": [self._bad_steps],
                        "FoundInfinite": [found_inf]},
                outputs={"LossScaling": [self._loss_scaling],
                         "OutGoodSteps": [self._good_steps],
                         "OutBadSteps": [self._bad_steps]},
                attrs={"incr_every_n_steps": self._incr_every_n_steps,
                       "decr_every_n_nan_or_inf":
                           self._decr_every_n_nan_or_inf,
                       "incr_ratio": self._incr_ratio,
                       "decr_ratio": self._decr_ratio})
        new_pg = [(p, ug) for (p, _), ug in zip(params_grads, unscaled)]
        return self._optimizer.apply_gradients(new_pg)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads


def decorate(optimizer, amp_lists=None, init_loss_scaling=2 ** 15,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8,
             use_dynamic_loss_scaling=True, use_bf16=True):
    """parity: contrib/mixed_precision/decorator.py decorate."""
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists, init_loss_scaling, use_dynamic_loss_scaling,
        incr_every_n_steps, decr_every_n_nan_or_inf, incr_ratio, decr_ratio,
        use_bf16=use_bf16)
