from . import ctr_reader

__all__ = ["ctr_reader"]
