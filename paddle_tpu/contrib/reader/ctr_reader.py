"""CTR data reader (parity: python/paddle/fluid/contrib/reader/
ctr_reader.py:53 `ctr_reader` — the reference backs this with a C++
multi-threaded file reader feeding a blocking queue; here the host-side
parse pool is the threaded MultiSlot machinery's sibling: a PyReader
batch generator over a file-shard thread pool, overlapping parsing with
the jitted step the same way `Dataset` readers do).

Formats (ctr_reader.py docstring):
  csv:  label dense,dense,... sparse,sparse,...
  svm:  label slot:feasign slot:feasign ...
"""

import gzip

import numpy as np

__all__ = ["ctr_reader"]


def _open(path, file_type):
    if file_type == "gzip":
        return gzip.open(path, "rt")
    return open(path, "r")


def _parse_csv(line, dense_slot_index, sparse_slot_index):
    parts = line.strip().split(" ")
    label = int(parts[0])
    dense = []
    sparse = []
    for idx in dense_slot_index:
        dense.extend(float(x) for x in parts[idx].split(","))
    for idx in sparse_slot_index:
        sparse.append([int(x) for x in parts[idx].split(",")])
    return label, dense, sparse


def _parse_svm(line, slots):
    parts = line.strip().split(" ")
    label = int(parts[0])
    by_slot = {s: [] for s in slots}
    for tok in parts[1:]:
        slot, _, sign = tok.partition(":")
        slot = int(slot)
        if slot in by_slot:
            by_slot[slot].append(int(sign))
    return label, [by_slot[s] for s in slots]


def ctr_reader(feed_dict, file_type, file_format, dense_slot_index,
               sparse_slot_index, capacity, thread_num, batch_size,
               file_list, slots, name=None):
    """Build a PyReader over CTR text files (ctr_reader.py:53). Returns the
    PyReader; iterate it for feed dicts (the TPU path has no EOFException
    protocol — a pass ends when the iterator does)."""
    from ...reader import PyReader

    if file_type not in ("gzip", "plain"):
        raise ValueError("file_type must be 'gzip' or 'plain'")
    if file_format not in ("csv", "svm"):
        raise ValueError("file_format must be 'csv' or 'svm'")

    reader = PyReader(feed_list=feed_dict, capacity=capacity,
                      iterable=True)

    def batch_generator():
        labels, denses, sparses = [], [], []

        def emit():
            names = [v.name for v in feed_dict]
            cols = []
            cols.append(np.asarray(labels, np.int64).reshape(-1, 1))
            if dense_slot_index:
                cols.append(np.asarray(denses, np.float32))
            for j in range(len(sparses[0]) if sparses else 0):
                # ragged sparse slots pad with 0 to the batch max width
                rows = [s[j] for s in sparses]
                w = max(1, max(len(r) for r in rows))
                arr = np.zeros((len(rows), w), np.int64)
                for i, r in enumerate(rows):
                    arr[i, :len(r)] = r
                cols.append(arr)
            if len(cols) != len(names):
                raise ValueError(
                    "ctr_reader assembled %d columns (label%s + %d sparse "
                    "slots) but feed_dict has %d vars %r — declare one var "
                    "for the label, one for the combined dense features, "
                    "and one per sparse slot"
                    % (len(cols),
                       " + dense" if dense_slot_index else "",
                       len(cols) - 1 - (1 if dense_slot_index else 0),
                       len(names), names))
            return dict(zip(names, cols))

        for path in file_list:
            with _open(path, file_type) as f:
                for line in f:
                    if not line.strip():
                        continue
                    if file_format == "csv":
                        label, dense, sparse = _parse_csv(
                            line, dense_slot_index, sparse_slot_index)
                    else:
                        label, sparse = _parse_svm(line, slots)
                        dense = []
                    labels.append(label)
                    denses.append(dense)
                    sparses.append(sparse)
                    if len(labels) == batch_size:
                        yield emit()
                        labels, denses, sparses = [], [], []
        if labels:
            yield emit()

    reader.decorate_batch_generator(batch_generator)
    return reader
