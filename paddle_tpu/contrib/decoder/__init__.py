from .beam_search_decoder import (BeamSearchDecoder, InitState, StateCell,
                                  TrainingDecoder)

__all__ = ["BeamSearchDecoder", "InitState", "StateCell", "TrainingDecoder"]
