from .beam_search_decoder import (BeamSearchDecoder, StateCell,
                                  TrainingDecoder)

__all__ = ["BeamSearchDecoder", "StateCell", "TrainingDecoder"]
