"""Beam-search decoder DSL (parity: python/paddle/fluid/contrib/decoder/
beam_search_decoder.py — StateCell / TrainingDecoder / BeamSearchDecoder).

TPU-native shape: instead of the reference's LoD-lane machinery, decoding
runs the user's cell over a dense [batch, beam] layout; each step scores
candidates, calls the beam_search op (top-k over beam*K with finished-lane
handling) and stacks selections that beam_search_decode backtracks."""

import numpy as np

from ... import framework
from ...layer_helper import LayerHelper
from ... import layers as nn_layers
from ...layers import extras as extra_layers

__all__ = ["StateCell", "TrainingDecoder", "BeamSearchDecoder"]


class StateCell:
    """Named-state step cell (parity: beam_search_decoder.py StateCell).
    Register states + input slots, then provide a compute function that maps
    (inputs, states) -> (output scores, new states)."""

    def __init__(self, inputs, states, out_state=None, name=None):
        self._input_names = list(inputs)
        self._state_names = list(states)
        self._compute = None
        self.out_state = out_state

    def register_updater(self, fn):
        """fn(inputs: dict, states: dict) -> (scores_var, new_states dict)"""
        self._compute = fn
        return fn

    def compute_state(self, inputs, states):
        if self._compute is None:
            raise RuntimeError("StateCell has no registered updater")
        return self._compute(inputs, states)


class TrainingDecoder:
    """Teacher-forced unroll of a StateCell over gold sequences (parity:
    TrainingDecoder: same cell as decoding, run time-major)."""

    def __init__(self, state_cell, name=None):
        self.cell = state_cell

    def __call__(self, inputs_per_step, init_states):
        """inputs_per_step: {name: Variable [B, T, ...]}; returns stacked
        scores [B, T, V] built with the cell."""
        states = dict(init_states)
        outs = []
        T = next(iter(inputs_per_step.values())).shape[1]
        for t in range(T):
            step_in = {k: nn_layers.slice(v, axes=[1], starts=[t],
                                          ends=[t + 1])
                       for k, v in inputs_per_step.items()}
            step_in = {k: nn_layers.squeeze(v, axes=[1])
                       for k, v in step_in.items()}
            scores, states = self.cell.compute_state(step_in, states)
            outs.append(nn_layers.unsqueeze(scores, axes=[1]))
        return nn_layers.concat(outs, axis=1)


class BeamSearchDecoder:
    """Dense beam search driver (parity: BeamSearchDecoder.decode()).

    The user's cell maps token ids [B*W] + states -> next-token log-prob
    scores [B*W, V]; decode() expands beams, tracks finished lanes via
    end_id, and returns (sentence_ids [B, W, T], sentence_scores [B, W])."""

    def __init__(self, state_cell, init_ids, init_scores, target_dict_dim,
                 word_dim=None, input_var_dict=None, topk_size=None,
                 sparse_emb=True, max_candidate_level=None,
                 beam_size=4, end_id=1, max_len=16, name=None):
        self.cell = state_cell
        self.init_ids = init_ids
        self.init_scores = init_scores
        self.vocab_size = target_dict_dim
        self.beam_size = beam_size
        self.end_id = end_id
        self.max_len = max_len

    def decode(self, init_states):
        """Build the unrolled decode graph; returns (ids, scores) vars."""
        W, V = self.beam_size, self.vocab_size
        pre_ids = self.init_ids          # [B, W]
        pre_scores = self.init_scores    # [B, W]
        states = dict(init_states)
        step_ids, step_scores, step_parents = [], [], []
        k = min(2 * W, V)
        for t in range(self.max_len):
            flat_ids = nn_layers.reshape(pre_ids, shape=[-1])  # [B*W]
            scores, states = self.cell.compute_state(
                {"ids": flat_ids}, states)                     # [B*W, V]
            topv, topi = nn_layers.topk(scores, k=k)
            # [B, W, K] candidate continuations
            cand_scores = nn_layers.reshape(topv, shape=[-1, W, k])
            cand_ids = nn_layers.reshape(topi, shape=[-1, W, k])
            probs = nn_layers.exp(cand_scores)  # beam_search expects probs
            sel_ids, sel_scores, parents = extra_layers.beam_search(
                pre_ids, pre_scores, cand_ids, probs,
                beam_size=W, end_id=self.end_id)
            step_ids.append(nn_layers.unsqueeze(sel_ids, axes=[0]))
            step_scores.append(nn_layers.unsqueeze(sel_scores, axes=[0]))
            step_parents.append(nn_layers.unsqueeze(parents, axes=[0]))
            pre_ids, pre_scores = sel_ids, sel_scores
        ids_arr = nn_layers.concat(step_ids, axis=0)        # [T, B, W]
        scores_arr = nn_layers.concat(step_scores, axis=0)
        parents_arr = nn_layers.concat(step_parents, axis=0)
        return extra_layers.beam_search_decode(
            ids_arr, scores_arr, parents_arr, beam_size=W,
            end_id=self.end_id)

    # reference-API aliases
    def __call__(self, init_states):
        return self.decode(init_states)
