"""Beam-search decoder DSL (parity: python/paddle/fluid/contrib/decoder/
beam_search_decoder.py — StateCell / TrainingDecoder / BeamSearchDecoder).

TPU-native shape: instead of the reference's LoD-lane machinery, decoding
runs the user's cell over a dense [batch, beam] layout; each step scores
candidates, calls the beam_search op (top-k over beam*K with finished-lane
handling) and stacks selections that beam_search_decode backtracks."""

import contextlib

import numpy as np

from ... import framework
from ...layer_helper import LayerHelper
from ... import layers as nn_layers
from ...layers import extras as extra_layers

__all__ = ["InitState", "StateCell", "TrainingDecoder",
           "BeamSearchDecoder"]


class InitState:
    """Initial hidden-state holder (parity: beam_search_decoder.py:43
    InitState). Wraps an existing Variable, or creates a constant-filled
    one shaped like `init_boot` (`fill_constant_batch_size_like` — the
    dense stand-in for the reference's LoD-aware boot). `need_reorder` is
    accepted for API parity; the dense [batch, beam] layout here never
    reorders by LoD rank."""

    def __init__(self, init=None, shape=None, value=0.0, init_boot=None,
                 need_reorder=False, dtype="float32"):
        if init is not None:
            self._init = init
        elif init_boot is None:
            raise ValueError(
                "init_boot must be provided to infer the shape of "
                "InitState .\n")
        else:
            self._init = nn_layers.fill_constant_batch_size_like(
                input=init_boot, value=value, shape=shape, dtype=dtype)
        self._shape = shape
        self._value = value
        self._need_reorder = need_reorder
        self._dtype = dtype

    @property
    def value(self):
        return self._init

    @property
    def need_reorder(self):
        return self._need_reorder


class StateCell:
    """Named-state step cell (parity: beam_search_decoder.py StateCell).

    Two registration styles, matching the reference:
      - functional: `register_updater(fn)` with
        fn(inputs: dict, states: dict) -> (scores_var, new_states dict)
      - imperative: `@cell.state_updater` decorating fn(cell) that calls
        cell.get_input / cell.get_state / cell.set_state; drive it with
        compute_state(inputs) + update_states() inside a decoder block.
    """

    def __init__(self, inputs, states, out_state=None, name=None):
        self._inputs = (dict(inputs) if isinstance(inputs, dict)
                        else {n: None for n in inputs})
        self._init_states = (dict(states) if isinstance(states, dict)
                             else {n: None for n in states})
        self._input_names = list(self._inputs)
        self._state_names = list(self._init_states)
        self._compute = None
        self._updater = None
        self._out_state_name = out_state
        self._cur_inputs = {}
        self._cur_states = {}
        self._new_states = {}
        self._decoder = None  # set by TrainingDecoder.block()

    def register_updater(self, fn):
        """fn(inputs: dict, states: dict) -> (scores_var, new_states dict)"""
        self._compute = fn
        return fn

    def state_updater(self, fn):
        """Imperative updater decorator (parity: StateCell.state_updater):
        fn(cell) reads via get_input/get_state and writes via set_state."""
        self._updater = fn
        return fn

    def get_input(self, input_name):
        """Current step's value for a registered input slot (parity:
        StateCell.get_input)."""
        if input_name not in self._cur_inputs:
            raise ValueError("input %r not fed to compute_state"
                             % input_name)
        return self._cur_inputs[input_name]

    def get_state(self, state_name):
        """Current value of a registered state (parity:
        StateCell.get_state)."""
        if state_name not in self._cur_states:
            raise ValueError("state %r unknown (registered: %r)"
                             % (state_name, self._state_names))
        return self._cur_states[state_name]

    def set_state(self, state_name, state_value):
        """Stage a state's next value; committed by update_states()
        (parity: StateCell.set_state — raises on unknown names like the
        reference: a typo'd name would otherwise leave the real RNN
        memory stale every step with no error)."""
        if state_name not in self._state_names:
            raise ValueError("state %r unknown (registered: %r)"
                             % (state_name, self._state_names))
        self._new_states[state_name] = state_value

    def update_states(self):
        """Commit staged states — inside a TrainingDecoder block this also
        writes the RNN memories (parity: StateCell.update_states)."""
        for name, val in self._new_states.items():
            if self._decoder is not None and name in self._decoder._mems:
                self._decoder._drnn.update_memory(
                    self._decoder._mems[name], val)
            self._cur_states[name] = val
        self._new_states = {}

    def out_state(self):
        """The designated output state's current value (parity:
        StateCell.out_state)."""
        if self._out_state_name is None:
            raise ValueError("StateCell was built without out_state")
        return self._cur_states[self._out_state_name]

    def compute_state(self, inputs, states=None):
        if self._compute is not None:
            if states is None:
                states = dict(self._cur_states)
            return self._compute(inputs, states)
        if self._updater is None:
            raise RuntimeError("StateCell has no registered updater")
        self._cur_inputs = dict(inputs)
        if states is not None:
            self._cur_states = dict(states)
        self._updater(self)
        if states is not None:
            # functional call driving an imperative updater: commit + return
            self.update_states()
            return (self._cur_states.get(self._out_state_name),
                    dict(self._cur_states))
        return None


class TrainingDecoder:
    """Teacher-forced decoder over a StateCell (parity: TrainingDecoder).

    Two driving styles:
      - functional: `decoder(inputs_per_step, init_states)` unrolls the
        cell over [B, T, ...] inputs and stacks the scores.
      - imperative (reference style): build the step once inside
        `with decoder.block():` using step_input/static_input + the
        cell's get/set/update_states, then `decoder()` for the stacked
        outputs — lowered through DynamicRNN onto one lax.scan.
    """

    BEFORE_DECODER = 0
    IN_DECODER = 1
    AFTER_DECODER = 2

    def __init__(self, state_cell, name=None):
        self.cell = state_cell
        self.status = self.BEFORE_DECODER
        self._drnn = None
        self._mems = {}
        self._outputs = None

    @contextlib.contextmanager
    def block(self):
        """Step-definition scope (parity: TrainingDecoder.block)."""
        from ...layers.control_flow import DynamicRNN

        if self.status != self.BEFORE_DECODER:
            raise RuntimeError("decoder.block() may only open once")
        self.status = self.IN_DECODER
        self._drnn = DynamicRNN()
        cell = self.cell
        with self._drnn.block():
            for name, ist in cell._init_states.items():
                init_var = getattr(ist, "value", ist)
                if init_var is None:
                    raise ValueError(
                        "state %r needs an InitState/Variable to run an "
                        "imperative decoder block" % name)
                mem = self._drnn.memory(init=init_var)
                self._mems[name] = mem
                cell._cur_states[name] = mem
            cell._decoder = self
            yield
        cell._decoder = None
        self.status = self.AFTER_DECODER

    def step_input(self, x):
        """Per-step slice of a [B, T, ...] input (parity:
        TrainingDecoder.step_input)."""
        return self._drnn.step_input(x)

    def static_input(self, x):
        """Input visible unchanged at every step (parity:
        TrainingDecoder.static_input)."""
        return self._drnn.static_input(x)

    def output(self, *outputs):
        """Mark per-step outputs to be stacked time-major (parity:
        TrainingDecoder.output)."""
        self._drnn.output(*outputs)

    def __call__(self, inputs_per_step=None, init_states=None):
        if inputs_per_step is None:
            if self.status != self.AFTER_DECODER:
                raise RuntimeError(
                    "decoder() in imperative mode requires a completed "
                    "block()")
            if self._outputs is None:
                self._outputs = self._drnn()
            return self._outputs
        states = dict(init_states)
        outs = []
        T = next(iter(inputs_per_step.values())).shape[1]
        for t in range(T):
            step_in = {k: nn_layers.slice(v, axes=[1], starts=[t],
                                          ends=[t + 1])
                       for k, v in inputs_per_step.items()}
            step_in = {k: nn_layers.squeeze(v, axes=[1])
                       for k, v in step_in.items()}
            scores, states = self.cell.compute_state(step_in, states)
            outs.append(nn_layers.unsqueeze(scores, axes=[1]))
        return nn_layers.concat(outs, axis=1)


class BeamSearchDecoder:
    """Dense beam search driver (parity: BeamSearchDecoder.decode()).

    The user's cell maps token ids [B*W] + states -> next-token log-prob
    scores [B*W, V]; decode() expands beams, tracks finished lanes via
    end_id, and returns (sentence_ids [B, W, T], sentence_scores [B, W])."""

    def __init__(self, state_cell, init_ids, init_scores, target_dict_dim,
                 word_dim=None, input_var_dict=None, topk_size=None,
                 sparse_emb=True, max_candidate_level=None,
                 beam_size=4, end_id=1, max_len=16, name=None):
        self.cell = state_cell
        self.init_ids = init_ids
        self.init_scores = init_scores
        self.vocab_size = target_dict_dim
        self.beam_size = beam_size
        self.end_id = end_id
        self.max_len = max_len

    def decode(self, init_states):
        """Build the unrolled decode graph; returns (ids, scores) vars."""
        W, V = self.beam_size, self.vocab_size
        pre_ids = self.init_ids          # [B, W]
        pre_scores = self.init_scores    # [B, W]
        states = dict(init_states)
        step_ids, step_scores, step_parents = [], [], []
        k = min(2 * W, V)
        for t in range(self.max_len):
            flat_ids = nn_layers.reshape(pre_ids, shape=[-1])  # [B*W]
            scores, states = self.cell.compute_state(
                {"ids": flat_ids}, states)                     # [B*W, V]
            topv, topi = nn_layers.topk(scores, k=k)
            # [B, W, K] candidate continuations
            cand_scores = nn_layers.reshape(topv, shape=[-1, W, k])
            cand_ids = nn_layers.reshape(topi, shape=[-1, W, k])
            probs = nn_layers.exp(cand_scores)  # beam_search expects probs
            sel_ids, sel_scores, parents = extra_layers.beam_search(
                pre_ids, pre_scores, cand_ids, probs,
                beam_size=W, end_id=self.end_id)
            step_ids.append(nn_layers.unsqueeze(sel_ids, axes=[0]))
            step_scores.append(nn_layers.unsqueeze(sel_scores, axes=[0]))
            step_parents.append(nn_layers.unsqueeze(parents, axes=[0]))
            pre_ids, pre_scores = sel_ids, sel_scores
        ids_arr = nn_layers.concat(step_ids, axis=0)        # [T, B, W]
        scores_arr = nn_layers.concat(step_scores, axis=0)
        parents_arr = nn_layers.concat(step_parents, axis=0)
        return extra_layers.beam_search_decode(
            ids_arr, scores_arr, parents_arr, beam_size=W,
            end_id=self.end_id)

    @contextlib.contextmanager
    def block(self):
        """Imperative decode-step scope (parity: BeamSearchDecoder.block —
        the reference wraps the body in a While op; here it IS a
        `layers.While` with max_trip_count=max_len, so the body stays
        reverse-capable and XLA sees one lax.scan)."""
        from ...layers import tensor as tensor_layers
        from ...layers.control_flow import While

        self._counter = tensor_layers.fill_constant(
            shape=[1], dtype="int64", value=0)
        max_len_v = tensor_layers.fill_constant(
            shape=[1], dtype="int64", value=self.max_len)
        self._cond = nn_layers.less_than(self._counter, max_len_v)
        # early_stop() raises this flag; it is ANDed into the condition at
        # the end of the body, so the write survives the counter update
        self._stop = tensor_layers.fill_constant(
            shape=[1], dtype="bool", value=False)
        self._loop_arrays = []
        w = While(self._cond, max_trip_count=self.max_len)
        with w.block():
            yield
            nn_layers.increment(self._counter, value=1, in_place=True)
            live = nn_layers.less_than(self._counter, max_len_v)
            nn_layers.logical_and(live, nn_layers.logical_not(self._stop),
                                  out=self._cond)

    def read_array(self, init, is_ids=False, is_scores=False):
        """Loop-carried array value (parity: BeamSearchDecoder.read_array):
        returns a var initialized to `init` outside the loop and carried
        across iterations via update_array's in-place write."""
        from ...layers.control_flow import _in_parent_block

        with _in_parent_block():
            v = nn_layers.assign(init)
        self._loop_arrays.append(v)
        return v

    def update_array(self, array, value):
        """Write an array's next-iteration value (parity:
        BeamSearchDecoder.update_array)."""
        nn_layers.assign(value, output=array)

    def early_stop(self):
        """Terminate the decode loop after this iteration (parity:
        BeamSearchDecoder.early_stop): raises the stop flag that the
        end-of-body condition update ANDs in."""
        from ...layers import tensor as tensor_layers

        true_v = tensor_layers.fill_constant(shape=[1], dtype="bool",
                                             value=True)
        nn_layers.assign(true_v, output=self._stop)

    # reference-API aliases
    def __call__(self, init_states=None):
        if init_states is None:
            return list(self._loop_arrays)
        return self.decode(init_states)
