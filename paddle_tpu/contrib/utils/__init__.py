"""Contrib utils (parity: python/paddle/fluid/contrib/utils/ —
lookup-table helpers + HDFS client re-export)."""

from .lookup_table_utils import (convert_dist_to_sparse_program,
                                 load_persistables_for_increment,
                                 load_persistables_for_inference)

__all__ = ["convert_dist_to_sparse_program",
           "load_persistables_for_increment",
           "load_persistables_for_inference"]
