"""Distributed-lookup-table helpers (parity: python/paddle/fluid/contrib/
utils/lookup_table_utils.py:82 `convert_dist_to_sparse_program`, :133
`load_persistables_for_increment`, :257 `load_persistables_for_inference`).

TPU-native mapping: the reference splits a giant embedding across pservers
and rewrites lookups into prefetch RPCs; here the distributed table is a
host-side `HostEmbeddingTable` behind `distributed_embedding`
(parallel/host_embedding.py), and `lookup_table` ops carry
`is_distributed=True`. Converting back for local inference flips those
lookups to plain device-resident gathers."""

import logging
import os

import numpy as np

_logger = logging.getLogger(__name__)

__all__ = ["convert_dist_to_sparse_program",
           "load_persistables_for_increment",
           "load_persistables_for_inference"]


def _distributed_lookup_ops(program):
    ops = []
    for block in program.blocks:
        for op in block.ops:
            if op.type in ("lookup_table", "fused_embedding_seq_pool") \
                    and op.attrs.get("is_distributed"):
                ops.append(op)
    return ops


def convert_dist_to_sparse_program(program):
    """Rewrite distributed lookups into local ones so a trainer program
    can run inference without the parameter-server/host table
    (lookup_table_utils.py:82). Returns the same program, mutated."""
    ops = _distributed_lookup_ops(program)
    if not ops:
        _logger.warning(
            "There are no distributed lookup tables need to be converted")
        return program
    for op in ops:
        op.attrs["is_distributed"] = False
        op.attrs["is_sparse"] = True
    program._bump_version()
    return program


def _load_table_var(scope, name, path):
    if os.path.isdir(path):
        # sharded directory: shard_N.npy files stacked in order
        shards = sorted(
            (f for f in os.listdir(path) if f.endswith(".npy")),
            key=lambda f: int("".join(ch for ch in f if ch.isdigit()) or 0))
        arrays = [np.load(os.path.join(path, f)) for f in shards]
        value = np.concatenate(arrays, axis=0) if len(arrays) > 1 \
            else arrays[0]
    else:
        if not os.path.exists(path) and os.path.exists(path + ".npy"):
            path += ".npy"
        value = np.load(path)
    scope.set(name, value)
    return value


def load_persistables_for_increment(dirname, executor, program,
                                    lookup_table_var, lookup_table_var_path):
    """Resume incremental training: load every persistable EXCEPT the
    lookup table from `dirname`, then load the (possibly sharded) table
    from its own path (lookup_table_utils.py:133)."""
    from ... import io
    from ...core.scope import global_scope

    table_name = (lookup_table_var if isinstance(lookup_table_var, str)
                  else lookup_table_var.name)
    vars_ = [v for v in program.list_vars()
             if v.persistable and v.name != table_name
             and not v.name.startswith("__")]
    io.load_vars(executor, dirname, main_program=program, vars=vars_)
    _load_table_var(global_scope(), table_name, lookup_table_var_path)


def load_persistables_for_inference(dirname, executor, program,
                                    lookup_table_var_name):
    """Load an inference program's persistables plus its lookup table
    saved under `dirname` (lookup_table_utils.py:257)."""
    from ... import io
    from ...core.scope import global_scope

    vars_ = [v for v in program.list_vars()
             if v.persistable and v.name != lookup_table_var_name
             and not v.name.startswith("__")]
    io.load_vars(executor, dirname, main_program=program, vars=vars_)
    table_path = os.path.join(dirname, lookup_table_var_name)
    if os.path.exists(table_path) or os.path.exists(table_path + ".npy"):
        _load_table_var(global_scope(), lookup_table_var_name, table_path)
    else:
        # table stored like any other persistable (single-host case)
        io.load_vars(executor, dirname, main_program=program,
                     vars=[program.global_block().var(
                         lookup_table_var_name)])
