"""Op-frequency statistics (parity: python/paddle/fluid/contrib/
op_frequence.py:23 `op_freq_statistic`)."""

from collections import OrderedDict

from ..framework import Program

__all__ = ["op_freq_statistic"]


def op_freq_statistic(program):
    """Count single-op and adjacent-op-pair frequencies over the global
    block (op_frequence.py:23). Adjacency follows the reference's
    producer->consumer definition: op B is adjacent to op A when B consumes
    an output of A (parameter outputs excluded), not mere list order.

    Returns (uni_op_freq, adj_2_op_freq) — both sorted descending, as
    lists of (key, count)."""
    if not isinstance(program, Program):
        raise TypeError("The input type should be Program. "
                        "But you passed in %s" % (type(program)))

    uni_op_freq = OrderedDict()
    adj_2_op_freq = OrderedDict()
    parameters = {p.name for p in program.global_block().all_parameters()}

    producer = {}  # var name -> producing op type
    for op in program.global_block().ops:
        uni_op_freq[op.type] = uni_op_freq.get(op.type, 0) + 1
        for name in op.input_names():
            prev = producer.get(name)
            if prev is not None:
                key = "%s->%s" % (prev, op.type)
                adj_2_op_freq[key] = adj_2_op_freq.get(key, 0) + 1
        for name in op.output_names():
            if name not in parameters:
                producer[name] = op.type

    uni = sorted(uni_op_freq.items(), key=lambda kv: -kv[1])
    adj = sorted(adj_2_op_freq.items(), key=lambda kv: -kv[1])
    return uni, adj
