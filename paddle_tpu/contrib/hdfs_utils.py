"""HDFS shell wrapper (parity: python/paddle/fluid/contrib/utils/
hdfs_utils.py HDFSClient — drives the `hadoop fs` CLI with retries; plus
multi_download/multi_upload helpers).

Gated: every call shells out to ${hadoop_home}/bin/hadoop; environments
without a hadoop install get a clear error instead of an import failure.
"""

import os
import subprocess
import time

__all__ = ["HDFSClient", "multi_download", "multi_upload"]


class HDFSClient:
    def __init__(self, hadoop_home, configs):
        self.pre_commands = []
        hadoop_bin = os.path.join(hadoop_home, "bin", "hadoop")
        self.pre_commands.append(hadoop_bin)
        self.pre_commands.append("fs")
        for k, v in (configs or {}).items():
            self.pre_commands.extend(["-D", "%s=%s" % (k, v)])
        self._hadoop_bin = hadoop_bin

    def _run(self, commands, retry_times=5):
        if not os.path.exists(self._hadoop_bin):
            raise RuntimeError(
                "hadoop binary not found at %s" % self._hadoop_bin)
        cmd = self.pre_commands + commands
        retry_times = max(int(retry_times), 1)
        for attempt in range(retry_times):
            proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                    stderr=subprocess.PIPE)
            out, err = proc.communicate()
            if proc.returncode == 0:
                return 0, out.decode("utf-8", "replace")
            if attempt < retry_times - 1:
                time.sleep(min(2 ** attempt, 30))
        return proc.returncode, err.decode("utf-8", "replace")

    def is_exist(self, hdfs_path=None):
        code, _ = self._run(["-test", "-e", hdfs_path], retry_times=1)
        return code == 0

    def is_dir(self, hdfs_path=None):
        code, _ = self._run(["-test", "-d", hdfs_path], retry_times=1)
        return code == 0

    def upload(self, hdfs_path, local_path, overwrite=False, retry_times=5):
        if overwrite and self.is_exist(hdfs_path):
            self.delete(hdfs_path)
        code, _ = self._run(["-put", local_path, hdfs_path], retry_times)
        return code == 0

    def download(self, hdfs_path, local_path, overwrite=False, unzip=False):
        if overwrite and os.path.exists(local_path):
            os.remove(local_path)
        code, _ = self._run(["-get", hdfs_path, local_path])
        if code == 0 and unzip and local_path.endswith(".gz"):
            import gzip
            import shutil

            target = local_path[:-3]
            with gzip.open(local_path, "rb") as src, \
                    open(target, "wb") as dst:
                shutil.copyfileobj(src, dst)
        return code == 0

    def delete(self, hdfs_path):
        code, _ = self._run(["-rm", "-r", hdfs_path])
        return code == 0

    def rename(self, hdfs_src_path, hdfs_dst_path, overwrite=False):
        if overwrite and self.is_exist(hdfs_dst_path):
            self.delete(hdfs_dst_path)
        code, _ = self._run(["-mv", hdfs_src_path, hdfs_dst_path])
        return code == 0

    def makedirs(self, hdfs_path):
        code, _ = self._run(["-mkdir", "-p", hdfs_path])
        return code == 0

    @staticmethod
    def make_local_dirs(local_path):
        os.makedirs(local_path, exist_ok=True)

    def ls(self, hdfs_path):
        code, out = self._run(["-ls", hdfs_path])
        if code != 0:
            return []
        files = []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) >= 8:
                files.append(parts[-1])
        return files

    def lsr(self, hdfs_path, only_file=True, sort=True):
        code, out = self._run(["-lsr", hdfs_path])
        if code != 0:
            return []
        entries = []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            if only_file and parts[0].startswith("d"):
                continue
            entries.append((parts[-1], " ".join(parts[5:7])))
        if sort:
            entries.sort(key=lambda e: e[1])
        return [e[0] for e in entries]


def _shard(datas, trainer_id, trainers):
    return datas[trainer_id::trainers]


def multi_download(client, hdfs_path, local_path, trainer_id, trainers,
                   multi_processes=5):
    """Download this trainer's shard of files under hdfs_path (reference
    hdfs_utils.py:437 — round-robin file split across trainers, fetched
    with a pool of workers)."""
    from multiprocessing.pool import ThreadPool

    client.make_local_dirs(local_path)
    all_files = client.lsr(hdfs_path)
    my_files = _shard(all_files, trainer_id, trainers)

    def _local(f):
        # preserve the remote directory structure — distinct shards often
        # share basenames (shard0/part-00000, shard1/part-00000)
        rel = os.path.relpath(f, hdfs_path) if f.startswith(
            hdfs_path.rstrip("/") + "/") else f.lstrip("/")
        return os.path.join(local_path, rel)

    def _fetch(f):
        target = _local(f)
        os.makedirs(os.path.dirname(target), exist_ok=True)
        client.download(f, target)

    with ThreadPool(max(int(multi_processes), 1)) as pool:
        pool.map(_fetch, my_files)
    return [_local(f) for f in my_files]


def multi_upload(client, hdfs_path, local_path, multi_processes=5,
                 overwrite=False):
    from multiprocessing.pool import ThreadPool

    client.makedirs(hdfs_path)
    jobs = []
    for root, _, files in os.walk(local_path):
        for f in files:
            local_file = os.path.join(root, f)
            rel = os.path.relpath(local_file, local_path)
            jobs.append((os.path.join(hdfs_path, rel), local_file))
    with ThreadPool(max(int(multi_processes), 1)) as pool:
        pool.map(lambda j: client.upload(j[0], j[1], overwrite=overwrite),
                 jobs)
