"""Program memory estimation (parity: python/paddle/fluid/contrib/
memory_usage_calc.py memory_usage)."""

import numpy as np

from .. import framework

__all__ = ["memory_usage"]

_DTYPE_SIZE = {"float16": 2, "bfloat16": 2, "float32": 4, "float64": 8,
               "int8": 1, "int16": 2, "int32": 4, "int64": 8, "uint8": 1,
               "bool": 1}


def memory_usage(program, batch_size=1):
    """Rough activation+param footprint of a program in MB, resolving -1
    batch dims with batch_size (memory_usage_calc.py:memory_usage)."""
    if program is None:
        program = framework.default_main_program()
    total = 0
    for var in program.list_vars():
        shape = var.shape
        if shape is None:
            continue
        numel = 1
        for d in shape:
            numel *= batch_size if d in (-1, None) else d
        total += numel * _DTYPE_SIZE.get(str(var.dtype), 4)
    mb = total / (1024.0 ** 2)
    return mb, mb * 0.8, mb * 1.2  # (estimate, low, high) like the reference
