"""Pruning strategies (parity: contrib/slim/prune/ — magnitude and
sensitivity pruners operating on scope weights)."""

import numpy as np

from ...core.scope import global_scope

__all__ = ["MagnitudePruner", "SensitivePruner", "prune_by_ratio"]


def prune_by_ratio(weight, ratio):
    """Zero the smallest-|w| `ratio` fraction of entries; returns (pruned,
    mask)."""
    w = np.asarray(weight)
    k = int(w.size * ratio)
    if k == 0:
        return w, np.ones_like(w, bool)
    thresh = np.partition(np.abs(w).ravel(), k - 1)[k - 1]
    mask = np.abs(w) > thresh
    return w * mask, mask


class MagnitudePruner:
    """parity: slim MagnitudePruner — one-shot magnitude pruning of named
    params in the scope; masks are remembered so apply_masks() can re-zero
    after optimizer steps (iterative-magnitude-pruning loop)."""

    def __init__(self, ratio=0.5, scope=None):
        self.ratio = ratio
        self._scope = scope
        self.masks = {}

    @property
    def scope(self):
        return self._scope or global_scope()

    def prune(self, param_names):
        stats = {}
        for name in param_names:
            w = self.scope.get(name)
            if w is None:
                raise KeyError("param %r not in scope" % name)
            pruned, mask = prune_by_ratio(w, self.ratio)
            self.scope.set(name, pruned)
            self.masks[name] = mask
            stats[name] = 1.0 - mask.mean()
        return stats

    def apply_masks(self):
        for name, mask in self.masks.items():
            w = self.scope.get(name)
            self.scope.set(name, np.asarray(w) * mask)


class SensitivePruner(MagnitudePruner):
    """Pick per-param ratios by loss sensitivity: params whose pruning
    degrades `eval_fn` least are pruned hardest (parity:
    slim/prune sensitive pruning)."""

    def sensitivities(self, param_names, eval_fn, ratios=(0.1, 0.3, 0.5)):
        base = float(eval_fn())
        table = {}
        for name in param_names:
            orig = np.asarray(self.scope.get(name)).copy()
            table[name] = []
            for r in ratios:
                pruned, _ = prune_by_ratio(orig, r)
                self.scope.set(name, pruned)
                table[name].append(float(eval_fn()) - base)
            self.scope.set(name, orig)
        return table

    def prune_sensitive(self, param_names, eval_fn, budget_ratio=0.5,
                        ratios=(0.1, 0.3, 0.5)):
        sens = self.sensitivities(param_names, eval_fn, ratios)
        # hardest pruning to the least-sensitive params
        order = sorted(param_names,
                       key=lambda n: abs(sens[n][-1]))
        stats = {}
        for i, name in enumerate(order):
            self.ratio = budget_ratio if i < len(order) // 2 else \
                budget_ratio / 2
            stats.update(self.prune([name]))
        return stats
