"""Distillation losses (parity: contrib/slim/distillation/ — FSP, L2 and
soft-label losses combined into the student's objective)."""

from ...layers import extras as extra_layers
from ...layers import nn as nn_layers

__all__ = ["fsp_loss", "l2_loss", "soft_label_loss"]


def fsp_loss(teacher_var1, teacher_var2, student_var1, student_var2):
    """Flow-of-solution-procedure distillation loss (fsp DistillationLoss)."""
    t = extra_layers.fsp_matrix(teacher_var1, teacher_var2)
    s = extra_layers.fsp_matrix(student_var1, student_var2)
    diff = nn_layers.elementwise_sub(t, s)
    return nn_layers.reduce_mean(nn_layers.square(diff))


def l2_loss(teacher_var, student_var):
    diff = nn_layers.elementwise_sub(teacher_var, student_var)
    return nn_layers.reduce_mean(nn_layers.square(diff))


def soft_label_loss(teacher_var, student_var, teacher_temperature=2.0,
                    student_temperature=2.0):
    t = nn_layers.softmax(nn_layers.scale(teacher_var,
                                          scale=1.0 / teacher_temperature))
    s = nn_layers.softmax(nn_layers.scale(student_var,
                                          scale=1.0 / student_temperature))
    return nn_layers.reduce_mean(nn_layers.cross_entropy(
        s, t, soft_label=True))
