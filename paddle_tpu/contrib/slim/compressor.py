"""Compression orchestrator (parity: python/paddle/fluid/contrib/slim/core/
compressor.py — Context + Compressor driving prune/quant/distill strategies
through epoch begin/end hooks, with checkpoint/eval plumbing).

TPU-native shape: the strategies operate on the Program IR + parameter
scope directly (no graph wrapper classes); training runs through the
standard Executor so every strategy edit is picked up by the next jitted
step compilation.
"""

import os
import pickle

import numpy as np

from ... import framework
from ...core.scope import global_scope

__all__ = ["Context", "Compressor"]


class Context:
    """Carries train/eval state across strategy hooks (reference
    compressor.py:72)."""

    def __init__(self, place=None, scope=None, train_graph=None,
                 train_reader=None, eval_graph=None, eval_reader=None,
                 teacher_graphs=None, train_optimizer=None,
                 distiller_optimizer=None):
        self.place = place
        self.scope = scope or global_scope()
        self.train_graph = train_graph
        self.train_reader = train_reader
        self.eval_graph = eval_graph
        self.eval_reader = eval_reader
        self.teacher_graphs = teacher_graphs or []
        self.train_optimizer = train_optimizer
        self.distiller_optimizer = distiller_optimizer
        self.epoch_id = 0
        self.eval_results = {}
        self._cache = {}

    def put(self, key, value):
        self._cache[key] = value

    def get(self, key):
        return self._cache.get(key)

    def eval_converged(self, metric_name, delta=0.001):
        results = self.eval_results.get(metric_name, [])
        if len(results) < 2:
            return False
        return abs(results[-1] - results[-2]) < delta

    def to_file(self, file_name):
        with open(file_name, "wb") as f:
            pickle.dump({"epoch_id": self.epoch_id,
                         "eval_results": self.eval_results,
                         "cache": self._cache}, f)

    def from_file(self, file_name):
        with open(file_name, "rb") as f:
            data = pickle.load(f)
        self.epoch_id = data["epoch_id"]
        self.eval_results = data["eval_results"]
        self._cache = data["cache"]


class Compressor:
    """Run a training loop with compression strategies hooked at epoch
    boundaries (reference compressor.py:207).

    Strategies are objects with optional hooks:
      on_compression_begin/end(context)
      on_epoch_begin/end(context)
    The built-in pruners (slim.prune.MagnitudePruner), the
    QuantizeTranspiler, and distillation losses (slim.distillation) all
    plug in through thin strategy adapters or direct calls from hooks.
    """

    def __init__(self, place, scope, train_program, train_reader=None,
                 train_feed_list=None, train_fetch_list=None,
                 eval_program=None, eval_reader=None, eval_feed_list=None,
                 eval_fetch_list=None, teacher_programs=None,
                 checkpoint_path="./checkpoints", train_optimizer=None,
                 distiller_optimizer=None, epoch=1):
        self.place = place
        self.scope = scope or global_scope()
        self.train_program = train_program
        self.train_reader = train_reader
        self.train_feed_list = train_feed_list or []
        self.train_fetch_list = train_fetch_list or []
        self.eval_program = eval_program
        self.eval_reader = eval_reader
        self.eval_feed_list = eval_feed_list or []
        self.eval_fetch_list = eval_fetch_list or []
        self.teacher_programs = teacher_programs or []
        self.checkpoint_path = checkpoint_path
        self.epoch = epoch
        self.strategies = []
        self.context = Context(
            place=place, scope=self.scope, train_graph=train_program,
            train_reader=train_reader, eval_graph=eval_program,
            eval_reader=eval_reader, teacher_graphs=self.teacher_programs,
            train_optimizer=train_optimizer,
            distiller_optimizer=distiller_optimizer)

    def add_strategy(self, strategy):
        self.strategies.append(strategy)
        return self

    def config(self, config_file):
        """Load strategies from a config file: a python file defining
        `strategies = [...]` (the YAML factory of the reference is replaced
        by plain python config — no yaml dep in this environment)."""
        namespace = {}
        with open(config_file) as f:
            exec(compile(f.read(), config_file, "exec"), namespace)
        for s in namespace.get("strategies", []):
            self.add_strategy(s)
        return self

    def _hook(self, name):
        for s in self.strategies:
            fn = getattr(s, name, None)
            if fn is not None:
                fn(self.context)

    def _train_one_epoch(self, exe):
        if self.train_reader is None:
            return
        from ...data_feeder import DataFeeder

        feeder = DataFeeder(self.train_feed_list) \
            if self.train_feed_list else None
        for batch in self.train_reader():
            feed = feeder.feed(batch) if feeder else batch
            exe.run(self.train_program, feed=feed,
                    fetch_list=self.train_fetch_list)

    def _eval(self, exe):
        if self.eval_program is None or self.eval_reader is None:
            return
        from ...data_feeder import DataFeeder

        feeder = DataFeeder(self.eval_feed_list) \
            if self.eval_feed_list else None
        totals = None
        n = 0
        for batch in self.eval_reader():
            feed = feeder.feed(batch) if feeder else batch
            vals = exe.run(self.eval_program, feed=feed,
                           fetch_list=self.eval_fetch_list)
            vals = [float(np.asarray(v).mean()) for v in vals]
            totals = vals if totals is None else [
                a + b for a, b in zip(totals, vals)]
            n += 1
        if totals:
            for fetch, total in zip(self.eval_fetch_list, totals):
                name = getattr(fetch, "name", str(fetch))
                self.context.eval_results.setdefault(name, []).append(
                    total / n)

    def _save_checkpoint(self):
        if not self.checkpoint_path:
            return
        d = os.path.join(self.checkpoint_path,
                         str(self.context.epoch_id))
        os.makedirs(d, exist_ok=True)
        self.context.to_file(os.path.join(d, "context"))
        from ... import io
        from ...executor import Executor

        io.save_persistables(Executor(self.place), d,
                             main_program=self.train_program)

    def _load_checkpoint(self):
        """Resume from the latest epoch checkpoint if one exists
        (reference compressor.py:330)."""
        if not self.checkpoint_path or not os.path.isdir(
                self.checkpoint_path):
            return
        epochs = sorted((int(d) for d in os.listdir(self.checkpoint_path)
                         if d.isdigit()), reverse=True)
        for epoch in epochs:
            d = os.path.join(self.checkpoint_path, str(epoch))
            ctx_file = os.path.join(d, "context")
            if not os.path.exists(ctx_file):
                continue
            self.context.from_file(ctx_file)
            from ... import io
            from ...executor import Executor

            io.load_persistables(Executor(self.place), d,
                                 main_program=self.train_program)
            self.context.epoch_id += 1   # saved epoch finished; resume next
            return

    def run(self):
        from ...core.scope import scope_guard
        from ...executor import Executor

        exe = Executor(self.place)
        # all training/eval/checkpoint IO resolves names in the caller's
        # scope, not whatever global scope happens to be active
        with scope_guard(self.scope):
            self._load_checkpoint()
            self._hook("on_compression_begin")
            for epoch_id in range(self.context.epoch_id, self.epoch):
                self.context.epoch_id = epoch_id
                self._hook("on_epoch_begin")
                self._train_one_epoch(exe)
                self._hook("on_epoch_end")
                self._eval(exe)
                self._save_checkpoint()
            self._hook("on_compression_end")
        return self.context
