"""Model-slimming toolkit (parity: python/paddle/fluid/contrib/slim/ —
prune / quantization / distillation strategies)."""

from .prune import MagnitudePruner, SensitivePruner, prune_by_ratio
from .distillation import fsp_loss, l2_loss, soft_label_loss
from .compressor import Compressor, Context

__all__ = ["MagnitudePruner", "SensitivePruner", "prune_by_ratio",
           "fsp_loss", "l2_loss", "soft_label_loss", "Compressor", "Context"]
