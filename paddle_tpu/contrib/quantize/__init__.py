from .quantize_transpiler import QuantizeTranspiler

__all__ = ["QuantizeTranspiler"]
