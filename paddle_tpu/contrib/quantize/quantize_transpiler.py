"""Quantization-aware-training transpiler (parity: python/paddle/fluid/
contrib/quantize/quantize_transpiler.py QuantizeTranspiler).

training_transpile: insert fake-quant(+dequant) ops on the inputs (weights
and activations) of quantizable ops so training sees int8 rounding noise.
freeze_program: switch activation quantizers to inference mode and bake the
weight quantization into the stored weights (scope edit), removing the
weight quantizers — the int8-deploy shape of the reference."""

import numpy as np

from ... import framework
from ...core.scope import global_scope

__all__ = ["QuantizeTranspiler"]

_QUANTIZABLE = ("conv2d", "depthwise_conv2d", "mul", "matmul")
# input slots carrying weights for each quantizable op type
_WEIGHT_SLOTS = {"conv2d": "Filter", "depthwise_conv2d": "Filter",
                 "mul": "Y", "matmul": "Y"}
_ACT_SLOTS = {"conv2d": "Input", "depthwise_conv2d": "Input",
              "mul": "X", "matmul": "X"}


class QuantizeTranspiler:
    def __init__(self, weight_bits=8, activation_bits=8,
                 activation_quantize_type="abs_max",
                 weight_quantize_type="abs_max", window_size=10000,
                 moving_rate=0.9):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.activation_quantize_type = activation_quantize_type
        self.weight_quantize_type = weight_quantize_type
        self.window_size = window_size
        self.moving_rate = moving_rate

    # -- train-time rewrite ----------------------------------------------

    def training_transpile(self, program=None, startup_program=None):
        program = program or framework.default_main_program()
        startup = startup_program or framework.default_startup_program()
        block = program.global_block()
        quantized = {}  # var name -> quantized var (reuse across consumers)
        new_ops = []
        for op in block.ops:
            if op.type in _QUANTIZABLE and not op.attrs.get("__quantized__"):
                for slot, is_weight in ((_ACT_SLOTS[op.type], False),
                                        (_WEIGHT_SLOTS[op.type], True)):
                    vs = op.inputs.get(slot, [])
                    if not vs:
                        continue
                    v = vs[0]
                    if v.name not in quantized:
                        qv, q_ops = self._insert_quant(
                            block, startup, v, is_weight)
                        quantized[v.name] = qv
                        new_ops.extend(q_ops)
                    op.inputs[slot] = [quantized[v.name]]
                op.attrs["__quantized__"] = True
            new_ops.append(op)
        block.ops = new_ops
        program._bump_version()
        return program

    def _insert_quant(self, block, startup, v, is_weight):
        from ...framework import Operator

        bits = self.weight_bits if is_weight else self.activation_bits
        qtype = (self.weight_quantize_type if is_weight
                 else self.activation_quantize_type)
        qv = block.create_var(name=v.name + ".quantized",
                              dtype=v.dtype, shape=v.shape)
        qv.shape = v.shape
        channel_wise = (qtype == "abs_max" and is_weight
                        and v.shape and len(v.shape) == 4)
        # channel-wise quantizers emit one scale PER output channel —
        # declare the var that way (the IR verifier checks declarations
        # against the fake_quantize_* infer rules)
        scale = block.create_var(name=v.name + ".scale", dtype=v.dtype,
                                 shape=(v.shape[0],) if channel_wise
                                 else (1,),
                                 persistable=True)
        ops = []
        if qtype == "abs_max":
            op_type = ("fake_channel_wise_quantize_abs_max"
                       if channel_wise
                       else "fake_quantize_abs_max")
            ops.append(Operator(
                block, op_type, inputs={"X": [v]},
                outputs={"Out": [qv], "OutScale": [scale]},
                attrs={"bit_length": bits}))
        else:  # moving_average_abs_max / range_abs_max
            sb = startup.global_block()
            if not sb.has_var(scale.name):
                from ...initializer import Constant

                sv = sb.create_var(name=scale.name, shape=(1,),
                                   dtype=v.dtype, persistable=True)
                Constant(1.0)(sv, sb)
            op_type = ("fake_quantize_moving_average_abs_max"
                       if qtype == "moving_average_abs_max"
                       else "fake_quantize_range_abs_max")
            ops.append(Operator(
                block, op_type,
                inputs={"X": [v], "InScale": [scale]},
                outputs={"Out": [qv], "OutScale": [scale]},
                attrs={"bit_length": bits,
                       "moving_rate": self.moving_rate,
                       "window_size": self.window_size}))
        return qv, ops

    # -- deploy-time freeze ----------------------------------------------

    def freeze_program(self, program, place=None, scope=None):
        """Bake weight quantization into stored weights and flip activation
        quantizers to inference mode."""
        scope = scope or global_scope()
        block = program.global_block()
        new_ops = []
        for op in block.ops:
            if op.type.startswith("fake_quantize") or \
                    op.type == "fake_channel_wise_quantize_abs_max":
                src = op.inputs["X"][0]
                val = scope.get(src.name)
                if val is not None and getattr(src, "persistable", False):
                    # weight: snap to the quant grid once, drop the op
                    w = np.asarray(val)
                    bnt = (1 << (self.weight_bits - 1)) - 1
                    if w.ndim == 4:
                        s = np.abs(w.reshape(w.shape[0], -1)).max(axis=1)
                        s = np.maximum(s, 1e-8).reshape(-1, 1, 1, 1)
                    else:
                        s = max(float(np.abs(w).max()), 1e-8)
                    wq = np.round(w / s * bnt) / bnt * s
                    qname = op.outputs["Out"][0].name
                    scope.set(qname, wq.astype(w.dtype))
                    # declare as persistable so the executor feeds it
                    block.var(qname).persistable = True
                    continue
                op.attrs["is_test"] = True
            new_ops.append(op)
        block.ops = new_ops
        program._bump_version()
        return program

    def convert_to_int8(self, program, place=None, scope=None, skip=()):
        """Store quantizable ops' weights as int8 (parity:
        quantize_transpiler.py:354 convert_to_int8): each persistable
        weight feeding a quantizable op is REPLACED by an int8 twin
        `<name>.int8` holding round(w / scale * 127) — the fp var loses
        persistable status and its scope copy, and a prepended `dequantize`
        op reconstructs it from the int8 values at run time (halving the
        serving weight footprint is the point; the runtime genuinely
        computes from the int8 store, unlike a side-car copy). The fp
        scale is kept on the int8 var (`quant_scale`). Ops touching a
        name in `skip` (any input or output var — the quant blacklist
        contract) keep their fp32 weights."""
        # lazy: quant imports ir/observability at module level — pulling
        # it in at convert time keeps contrib import-light
        from ... import quant as _quant

        scope = scope or global_scope()
        bnt = (1 << (self.weight_bits - 1)) - 1
        skip = frozenset(skip or ())
        quantizable = ("conv2d", "depthwise_conv2d", "mul", "matmul")
        # weights of SKIPPED ops are protected outright: converting one
        # via a non-skipped sharer would still demote+erase the fp32
        # copy the skipped op computes from (shared/tied weights)
        protected = set()
        if skip:
            for block in program.blocks:
                for op in block.ops:
                    if op.type in quantizable and (
                            (set(op.input_names())
                             | set(op.output_names())) & skip):
                        protected.update(
                            v.name for vs in op.inputs.values()
                            for v in vs
                            if getattr(v, "persistable", False))
        converted = {}
        saved_bytes = fp32_bytes = 0
        pending = []  # (var, int8 var, scale): prepend AFTER the scan —
        # prepend_op mid-iteration would mutate the list being walked
        for block in program.blocks:
            for op in list(block.ops):
                if op.type not in quantizable:
                    continue
                if skip and ((set(op.input_names())
                              | set(op.output_names())) & skip):
                    continue
                for slot, vs in op.inputs.items():
                    for v in vs:
                        if not getattr(v, "persistable", False):
                            continue
                        if v.name in converted or v.name in protected:
                            continue
                        w = scope.get(v.name)
                        if w is None:
                            continue
                        w = np.asarray(w)
                        scale = max(float(np.abs(w).max()), 1e-8)
                        q = _quant.quantize_to_int8(w, scale, qmax=bnt)
                        int8_name = v.name + ".int8"
                        iv = program.global_block().create_var(
                            name=int8_name, shape=v.shape, dtype="int8",
                            persistable=True)
                        iv.quant_scale = scale / bnt
                        scope.set(int8_name, q)
                        # the int8 twin is now the stored weight: demote
                        # the fp var to a runtime-computed value; erase at
                        # the OWNING scope (erase() itself only drops a
                        # scope's own binding, scope.cc EraseVars parity)
                        v.persistable = False
                        scope.erase_nearest(v.name)
                        pending.append((v, iv, scale))
                        converted[v.name] = int8_name
                        saved_bytes += max(w.nbytes - q.nbytes, 0)
                        fp32_bytes += w.nbytes
        for v, iv, scale in pending:
            program.global_block().prepend_op(
                type="dequantize",
                inputs={"Input": [iv]},
                outputs={"Output": [v]},
                attrs={"Scale": bnt / scale, "out_dtype": v.dtype},
            )
        if pending:
            _quant.record_weight_store(len(pending), saved_bytes,
                                       fp32_bytes)
        program._bump_version()
        return program
