"""Decoupled weight decay optimizer extension (parity:
python/paddle/fluid/contrib/extend_optimizer/
extend_optimizer_with_weight_decay.py:102
`extend_with_decoupled_weight_decay` — AdamW-style: the decay applies to
the PRE-update parameter value, outside the adaptive moments;
arXiv:1711.05101)."""

from ... import framework, optimizer as optimizer_mod

__all__ = ["DecoupledWeightDecay", "extend_with_decoupled_weight_decay"]


class DecoupledWeightDecay:
    """Mixin adding `new_param = optimized_param - coeff * old_param`
    after the base optimizer's update ops."""

    def __init__(self, weight_decay=0.0, apply_decay_param_fun=None,
                 **kwargs):
        if not isinstance(weight_decay, (int, float, framework.Variable)):
            raise TypeError("coeff should be float or Variable.")
        self._params_name = set()
        self._apply_decay_param_fun = apply_decay_param_fun
        self._coeff = weight_decay
        super().__init__(**kwargs)

    def _decay_ops(self, params_grads):
        from ... import layers

        if isinstance(self._coeff, (int, float)) and self._coeff == 0.0:
            return
        for param, grad in params_grads:
            if grad is None:
                continue
            if self._apply_decay_param_fun is not None \
                    and not self._apply_decay_param_fun(param.name):
                continue
            assert param.name not in self._params_name
            self._params_name.add(param.name)
            # scaled with the PRE-update value: snapshot before the base
            # optimizer's update op runs (the reference computes
            # param * coeff before apply_optimize for the same reason)
            scaled = layers.scale(param, scale=float(self._coeff)) \
                if isinstance(self._coeff, (int, float)) \
                else layers.elementwise_mul(param, self._coeff)
            yield param, scaled

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = self.backward(
            loss, startup_program=startup_program,
            parameter_list=parameter_list, no_grad_set=no_grad_set)
        from ... import layers

        scaled = list(self._decay_ops(params_grads) or ())
        optimize_ops = self.apply_gradients(params_grads)
        for param, scaled_param in scaled:
            updated = layers.elementwise_sub(param, scaled_param)
            layers.assign(updated, param)
        return optimize_ops, params_grads

    def __str__(self):
        return " ".join(["Weight Decay, params:",
                         ",".join(self._params_name)])


def extend_with_decoupled_weight_decay(base_optimizer):
    """Returns a subclass of `base_optimizer` with decoupled weight decay
    (extend_optimizer_with_weight_decay.py:102):

        AdamW = fluid.contrib.extend_with_decoupled_weight_decay(
            fluid.optimizer.Adam)
        AdamW(learning_rate=1e-3, weight_decay=0.01).minimize(loss)
    """
    if not (isinstance(base_optimizer, type)
            and issubclass(base_optimizer, optimizer_mod.Optimizer)):
        raise TypeError(
            "The input(base_optimizer) should be a derived class of "
            "Optimizer.")

    class OptimizerWithDecoupledWeightDecay(DecoupledWeightDecay,
                                            base_optimizer):
        def __init__(self, weight_decay, apply_decay_param_fun=None,
                     **kwargs):
            super().__init__(weight_decay, apply_decay_param_fun, **kwargs)

    return OptimizerWithDecoupledWeightDecay
