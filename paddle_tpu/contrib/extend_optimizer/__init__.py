from .extend_optimizer_with_weight_decay import (
    DecoupledWeightDecay, extend_with_decoupled_weight_decay)

__all__ = ["DecoupledWeightDecay", "extend_with_decoupled_weight_decay"]
