"""Post-training int8 calibration (parity: python/paddle/fluid/contrib/
int8_inference/utility.py Calibrator).

The reference Calibrator samples fp32 activations while running a saved
inference program, derives a per-tensor scale with the KL-divergence method
(TensorRT-style histogram search), and rewrites the program with
quantize/dequantize ops around quantizable ops. The TPU-native shape is the
same three phases, but the rewritten program carries `quantize`/`dequantize`
ops that lower to XLA int8 round-trips (ops/quant_ops.py).
"""

import numpy as np

from .. import framework
from ..core.scope import global_scope

__all__ = ["Calibrator"]

_QUANTIZABLE = ("conv2d", "depthwise_conv2d", "mul", "matmul")


_NUM_BINS = 2048


def _kl_scale(hist, amax, num_quantized_bins=255):
    """Histogram KL search for the saturation threshold (reference
    utility.py get_optimal_scaling_factor)."""
    num_bins = len(hist)
    if amax == 0.0 or hist.sum() == 0:
        return 1.0
    best_div, best_t = float("inf"), num_bins
    for t in range(num_quantized_bins, num_bins + 1, 16):
        p = hist[:t].astype(np.float64).copy()
        p[t - 1] += hist[t:].sum()  # clip outliers into last bin
        # quantize p into num_quantized_bins then expand back
        chunks = np.array_split(p, num_quantized_bins)
        q = np.concatenate([
            np.full(len(c), c.sum() / max((c > 0).sum(), 1)) * (c > 0)
            for c in chunks])
        p /= max(p.sum(), 1e-12)
        q /= max(q.sum(), 1e-12)
        mask = p > 0
        div = float(np.sum(p[mask] * np.log(p[mask] / np.maximum(q[mask], 1e-12))))
        if div < best_div:
            best_div, best_t = div, t
    return best_t * amax / num_bins


class Calibrator:
    """Collect activation statistics over sample batches, then emit an
    int8-annotated program.

    Usage (reference README flow):
        calib = Calibrator(program=infer_prog, pretrained_model=path,
                           algo="KL")
        for batch: exe.run(...); calib.sample_data()
        calib.save_int8_model()
    """

    def __init__(self, program=None, pretrained_model=None, algo="KL",
                 exe=None, output=None, feed_var_names=None,
                 fetch_list=None, scope=None):
        self.program = program or framework.default_main_program()
        self.algo = algo
        self.exe = exe
        self.output = output
        self.feed_var_names = feed_var_names
        self.fetch_list = fetch_list
        self.scope = scope or global_scope()
        # var name -> (histogram[_NUM_BINS], running abs-max); accumulated
        # incrementally so calibration memory is O(vars), not O(batches)
        self._stats = {}
        self._scales = {}

    def _watched_vars(self):
        """Only the input-slot vars — those are the ones save_int8_model
        annotates with scales."""
        names = set()
        for op in self.program.global_block().ops:
            if op.type in _QUANTIZABLE:
                for vs in op.inputs.values():
                    for v in vs:
                        names.add(v.name)
        return names

    def _accumulate(self, name, arr):
        amax_new = float(np.abs(arr).max()) if arr.size else 0.0
        hist_old, amax_old = self._stats.get(
            name, (np.zeros(_NUM_BINS, np.int64), 0.0))
        amax = max(amax_old, amax_new)
        if amax == 0.0:
            self._stats[name] = (hist_old, 0.0)
            return
        if amax > amax_old and hist_old.sum() > 0:
            # range grew: re-bin the old histogram onto the wider range
            old_centers = (np.arange(_NUM_BINS) + 0.5) * (amax_old / _NUM_BINS)
            idx = np.minimum(
                (old_centers / amax * _NUM_BINS).astype(np.int64),
                _NUM_BINS - 1)
            rebinned = np.zeros(_NUM_BINS, np.int64)
            np.add.at(rebinned, idx, hist_old)
            hist_old = rebinned
        hist_new, _ = np.histogram(np.abs(arr), bins=_NUM_BINS,
                                   range=(0, amax))
        self._stats[name] = (hist_old + hist_new, amax)

    def sample_data(self, fetched=None):
        """Fold activation values into the running histograms (call once
        per calibration batch). Weights are read from the scope; activation
        vars are not persisted by the functional executor, so pass them via
        `fetched` (dict name->array) or use run_and_sample()."""
        for name in self._watched_vars():
            if fetched is not None and name in fetched:
                arr = fetched[name]
            else:
                var = self.scope.find_var(name)
                if var is None or var.get_value() is None:
                    continue
                arr = var.get_value()
            self._accumulate(name, np.asarray(arr, dtype=np.float32))

    def watched_fetch_list(self):
        """Names of watched vars that must be fetched per batch (the
        non-persistable activations)."""
        persist = set()
        for v in self.program.global_block().vars.values():
            if getattr(v, "persistable", False):
                persist.add(v.name)
        return sorted(self._watched_vars() - persist)

    def run_and_sample(self, exe, feed):
        """Run one calibration batch, fetching the activations the scope
        does not retain, and fold everything into the histograms."""
        names = self.watched_fetch_list()
        vals = exe.run(self.program, feed=feed, fetch_list=list(names),
                       scope=self.scope)
        self.sample_data(dict(zip(names, map(np.asarray, vals))))

    def compute_scales(self):
        for name, (hist, amax) in self._stats.items():
            if self.algo == "KL":
                self._scales[name] = _kl_scale(hist, amax)
            else:  # "direct" / abs_max
                self._scales[name] = amax or 1.0
        return dict(self._scales)

    def save_int8_model(self):
        """Annotate quantizable ops with calibrated scales and persist the
        program if an output path was given."""
        if not self._scales:
            self.compute_scales()
        block = self.program.global_block()
        for op in block.ops:
            if op.type not in _QUANTIZABLE:
                continue
            for slot, vs in op.inputs.items():
                for v in vs:
                    if v.name in self._scales:
                        op.attrs["%s_scale" % slot] = self._scales[v.name]
            op.attrs["use_int8"] = True
        if self.output and self.exe is not None and self.feed_var_names:
            from .. import io
            io.save_inference_model(self.output, self.feed_var_names,
                                    self.fetch_list, self.exe,
                                    main_program=self.program)
        return self.program
