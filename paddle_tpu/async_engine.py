"""Asynchronous execution pipeline (the Fluid lineage's "dispatch step,
fetch results" loop, made real on TPU).

XLA dispatch is asynchronous: a jitted step returns device arrays that are
futures, and the host only stalls when something forces a host copy. The
seed executor threw that away by `np.asarray`-ing every fetch every step.
This module holds the pieces that keep N steps in flight end-to-end:

  LazyFetchList    — what `Executor.run(return_numpy=False)` (and every
                     `fetch_every_n` skipped step) returns: the fetches as
                     unmaterialized device futures. `as_numpy` (or
                     np.asarray on an element) is the ONE sync point.
  InflightWindow   — bounded count of dispatched-but-unsynced steps.
                     Admitting a step past the limit first materializes the
                     oldest step's fetches (host-transfer sync — reliable
                     where block_until_ready is not, e.g. the axon tunnel),
                     so device buffers can't grow without bound.
  FeedPrefetcher   — background thread that `jax.device_put`s the NEXT
                     batch (with its target sharding) while the current
                     step executes; preserves batch order; feeds the
                     `feed/h2d_bytes` / `feed/prefetch_depth` telemetry.
  DeferredWarns    — host-side accumulator for the packed runtime-warning
                     flags each step returns; materializes every few steps
                     instead of syncing the device every step.
  persistent cache — `PTPU_CACHE_DIR` wires jax's on-disk compilation
                     cache, plus a program-fingerprint manifest so
                     `compile_cache/persistent_hit|miss` can attribute
                     cross-process cache reuse to OUR cache key (XLA's
                     own key is the lowered HLO; the manifest threads the
                     framework-level fingerprint through it).

Sync-point contract (docs/ASYNC_EXECUTION.md): fetch values, scope state
and runtime warnings are only guaranteed observed after a sync — a
materialized fetch (`as_numpy`), a `fetch_every_n` boundary step, a
`return_numpy=True` run, `Executor.sync()`, or window backpressure.
Donated state buffers never alias a held fetch: XLA's copy insertion
gives every entry-computation output its own buffer, so a fetch handle
from step t stays valid (and keeps its step-t value) after step t+1
donates and overwrites the state — tests/test_async_exec.py pins this.
"""

import hashlib
import os
import queue as _queue
import threading

import numpy as np

from .observability import metrics as _metrics
from .observability import tracing as _tracing

__all__ = ["LazyFetchList", "InflightWindow", "FeedPrefetcher",
           "DeferredWarns", "HostStateStager", "as_numpy", "prefetch_iter",
           "setup_persistent_cache", "persistent_cache_dir",
           "note_compiled_program"]


def as_numpy(value):
    """THE sync point: materialize device fetch values as numpy. Accepts a
    single value, a list/tuple of values, or a LazyFetchList."""
    if isinstance(value, (list, tuple)):
        return [np.asarray(v) for v in value]
    return np.asarray(value)


class LazyFetchList(list):
    """Fetch results that have NOT been synced to host. Elements are the
    raw device arrays — futures under XLA async dispatch — so any numpy
    coercion (np.asarray, float(...)) is the materialization point."""

    def as_numpy(self):
        return [np.asarray(v) for v in self]


_concurrency = None


def _note_blocking(kind, site):
    """Concurrency-analysis hook (docs/STATIC_ANALYSIS.md): declare a
    blocking operation so PTPU_LOCK_CHECK=1 can flag a tracked lock held
    across it. Resolved lazily (this module imports during package
    bootstrap, before `paddle_tpu.analysis` exists); a no-op dict hit
    when tracking is off."""
    global _concurrency
    if _concurrency is None:
        from .analysis import concurrency as _c

        _concurrency = _c
    _concurrency.check_blocking(kind, site)


def _materialize(token):
    """Force one admitted step's fetches to host. np.asarray rather than
    block_until_ready: a host transfer is the sync that works everywhere
    (block_until_ready does not reliably block on the axon platform —
    bench.py round-3 measurement)."""
    _note_blocking("device-sync", "async_engine._materialize")
    if isinstance(token, (list, tuple)):
        for v in token:
            np.asarray(v)
    else:
        np.asarray(token)


class InflightWindow:
    """Bounded window of dispatched-but-unsynced steps (backpressure).

    `admit` registers one async step's fetch handles; when the window is
    full it first blocks on the OLDEST step, so at most `limit` steps of
    fetch/state buffers are ever pending on device. The
    `exec/inflight_steps` gauge records the window depth at each dispatch
    (it is deliberately not zeroed on sync — it reads as "how deep was
    the pipeline when a step was last dispatched")."""

    def __init__(self, limit=12):
        self.limit = max(1, int(limit))
        self._pending = []

    @property
    def depth(self):
        return len(self._pending)

    def admit(self, token):
        if token is None or (isinstance(token, (list, tuple))
                             and not token):
            return
        while len(self._pending) >= self.limit:
            _materialize(self._pending.pop(0))
        self._pending.append(token)
        _metrics.gauge("exec/inflight_steps").set(len(self._pending))

    def drain(self):
        """Block until every admitted step has materialized — the sync
        point behind Executor.sync(), resilience's preemption drain, and
        pre-checkpoint quiesce (docs/RESILIENCE.md)."""
        if not self._pending:
            return
        _metrics.counter("exec/window_drains").inc()
        with _tracing.span("window_drain", depth=len(self._pending)):
            while self._pending:
                _materialize(self._pending.pop(0))

    def reset(self):
        """Forget admitted steps without blocking — for callers that just
        synced the NEWEST step (device execution is in-order, so older
        steps are complete by then)."""
        del self._pending[:]


class DeferredWarns:
    """Deferred materialization for the per-step packed warning flags.

    The all-false common case must not cost a device sync per step, so
    each step's bool vector is merely kept (a device future); every
    `drain_every` steps — and at executor close/sync — the pending
    vectors are OR-reduced host-side and any newly-flagged label warns
    once. Labels are trace-static per compiled step, so every pending
    vector is congruent."""

    __slots__ = ("drain_every", "_labels", "_pending")

    def __init__(self, drain_every=8):
        self.drain_every = max(1, int(drain_every))
        self._labels = ()
        self._pending = []

    def add(self, labels, flags, warned):
        if not labels or not getattr(flags, "size", 0):
            return
        if all(label in warned for label in labels):
            return  # every label already fired: nothing left to observe
        self._labels = labels
        self._pending.append(flags)
        if len(self._pending) >= self.drain_every:
            self.drain(warned)

    def drain(self, warned):
        if not self._pending:
            return
        import warnings

        flagged = np.logical_or.reduce(
            [np.asarray(f) for f in self._pending])
        del self._pending[:]
        for label, hit in zip(self._labels, flagged):
            if hit and label not in warned:
                warned.add(label)
                warnings.warn(label, RuntimeWarning)


# ---------------------------------------------------------------------------
# feed prefetch
# ---------------------------------------------------------------------------


def _nbytes(vals):
    """Total buffer bytes across feed/fetch values without touching device
    memory (jax.Array.nbytes is shape metadata, not a transfer). The one
    byte-accounting helper behind executor/feed_bytes, executor/
    fetch_bytes and feed/h2d_bytes."""
    total = 0
    for v in vals:
        nb = getattr(v, "nbytes", None)
        if nb is not None:
            total += int(nb)
    return total


class FeedPrefetcher:
    """Background host->device double buffer for feed dicts.

    `put(feed)` hands a host batch to the worker thread, which
    `jax.device_put`s every value — with the target sharding from
    `sharding_fn(name, value)` when given (the compiled step's batch/seq
    sharding decision) — while the device executes the current step.
    `get()` returns staged batches strictly in put() order. At most
    `depth` batches are staged ahead (put() blocks past that — the same
    bounded-buffer contract as the in-flight window).

    `take_if_match(feed)` serves the raw feed-dict path: it returns the
    head staged batch only when it was built from exactly these value
    objects (identity match), so `Executor.prefetch(feed)` followed by
    `Executor.run(feed=feed)` transparently picks up the staged copy."""

    _CLOSE = object()

    def __init__(self, sharding_fn=None, depth=2, stage_fn=None):
        self._sharding_fn = sharding_fn
        self._stage_fn = stage_fn
        # unbounded queues + a slot semaphore: the WORKER never blocks
        # (so close() always reaches it), producers block in put() once
        # `depth` batches are staged ahead
        self._in = _queue.Queue()
        self._out = _queue.Queue()
        self._keys = _queue.Queue()
        self._slots = threading.Semaphore(max(1, int(depth)))
        self._thread = None
        self._closed = False

    # -- worker --------------------------------------------------------
    def _ensure_thread(self):
        if self._thread is None:
            t = threading.Thread(target=self._worker,
                                 name="ptpu-feed-prefetch", daemon=True)
            t.start()
            self._thread = t

    def _stage_one(self, name, value):
        if self._stage_fn is not None:
            return self._stage_fn(name, value)
        import jax

        if isinstance(value, jax.Array):
            return value  # already device-resident
        from .executor import check_feed_int64

        check_feed_int64(name, value)
        dt = getattr(value, "dtype", None)
        if dt is not None and np.dtype(dt) in (np.dtype(np.int64),
                                               np.dtype(np.uint64)):
            # keep 64-bit int slots host-side: device_put would
            # canonicalize them to int32 BEFORE the executor's declared-
            # dtype cast (and warn per batch); the step dispatch stages
            # them exactly as the unprefetched path does
            return value
        sharding = (self._sharding_fn(name, value)
                    if self._sharding_fn is not None else None)
        try:
            if sharding is not None:
                return jax.device_put(value, sharding)
            return jax.device_put(value)
        except (TypeError, ValueError):
            return value  # non-array feed entries pass through host-side

    def _worker(self):
        while True:
            item = self._in.get()
            if item is self._CLOSE:
                return
            try:
                staged = {k: self._stage_one(k, v) for k, v in item.items()}
                if _metrics.enabled():
                    _metrics.counter("feed/h2d_bytes").inc(
                        _nbytes(staged.values()))
                result = ("ok", staged)
            except BaseException as e:  # re-raised on the consumer side
                result = ("error", e)
            self._out.put(result)
            if _metrics.enabled():
                _metrics.gauge("feed/prefetch_depth").set(
                    self._out.qsize())

    # -- producer/consumer API -----------------------------------------
    def put(self, feed):
        """Queue one host feed dict for background staging. Blocks when
        `depth` batches are already staged ahead."""
        if self._closed:
            raise RuntimeError("FeedPrefetcher is closed")
        self._ensure_thread()
        _note_blocking("Semaphore.acquire", "feed_prefetcher.slots")
        self._slots.acquire()
        # strong refs to the SOURCE objects: identity matching via bare
        # id() would misfire when CPython reuses a freed array's address
        self._keys.put(dict(feed))
        self._in.put(dict(feed))

    def get(self):
        """Next staged device feed, in put() order."""
        _note_blocking("queue.get", "feed_prefetcher.out")
        self._keys.get()
        kind, payload = self._out.get()
        self._slots.release()
        if kind == "error":
            raise payload
        return payload

    def take_if_match(self, feed):
        """The head staged batch if it was built from exactly `feed`'s
        value objects; None otherwise (the staged queue is untouched)."""
        try:
            key = self._keys.queue[0]  # deque peek; GIL-atomic
        except IndexError:
            return None
        if len(key) != len(feed) or any(
                key.get(k) is not v for k, v in feed.items()):
            return None
        return self.get()

    def close(self):
        if self._closed:
            return
        self._closed = True
        if self._thread is not None:
            self._in.put(self._CLOSE)
            self._thread.join(timeout=5.0)


class HostStateStager:
    """Host<->device staging for host-offloaded optimizer state
    (docs/ZERO.md): m/v live in host RAM between steps; each step stages
    them to device for the sharded update and copies the updated shards
    back out.

    The H2D leg rides the FeedPrefetcher worker thread: `stage_in_begin`
    hands the host leaves to the worker (which `place_fn`s each one onto
    its target sharding) and returns immediately, so the transfer runs
    WHILE the backward/scatter jit — dispatched right after — executes;
    `stage_in_end` collects the staged device arrays at the point the
    update phase needs them. The D2H leg (`stage_out`) is a forced host
    copy (np.array — the same everywhere-reliable sync the in-flight
    window uses), which is also the step's optimizer-state sync point.
    Both directions count into the `counter` metric (zero/offload_bytes);
    the worker's own feed/h2d_bytes accounting sees the H2D leg too, as
    it is real host->device traffic."""

    def __init__(self, place_fn, counter="zero/offload_bytes"):
        self._prefetcher = FeedPrefetcher(
            stage_fn=lambda _name, value: place_fn(value))
        self._counter = counter
        self._pending_n = None

    def stage_in_begin(self, leaves):
        """Queue `leaves` (host arrays) for background placement."""
        if self._pending_n is not None:
            raise RuntimeError("stage_in_begin before the previous "
                               "stage_in_end was collected")
        self._pending_n = len(leaves)
        self._prefetcher.put({str(i): v for i, v in enumerate(leaves)})

    def stage_in_end(self):
        """The staged device arrays, in stage_in_begin order."""
        if self._pending_n is None:
            raise RuntimeError("stage_in_end without stage_in_begin")
        n, self._pending_n = self._pending_n, None
        staged = self._prefetcher.get()
        vals = [staged[str(i)] for i in range(n)]
        _metrics.counter(self._counter).inc(_nbytes(vals))
        return vals

    def abort(self):
        """Drop a begun-but-uncollected stage — error recovery for a
        caller whose compute phase failed between begin and end. The
        staged batch is collected and discarded so the worker slot frees
        and the next stage_in_begin starts clean. No-op when nothing is
        pending."""
        if self._pending_n is None:
            return
        self._pending_n = None
        try:
            self._prefetcher.get()
        except Exception:
            pass  # a staging error dies with the aborted step

    def stage_out(self, leaves):
        """Forced host copies of `leaves` (device arrays) — the D2H side.
        Blocks until the producing computation delivers."""
        out = [np.array(v) for v in leaves]
        _metrics.counter(self._counter).inc(_nbytes(out))
        return out

    def close(self):
        self._prefetcher.close()


def prefetch_iter(batches, prefetcher):
    """Drive `batches` (an iterable of host feed dicts) through a
    FeedPrefetcher with one-batch lookahead: while the consumer runs the
    step for batch k, the worker stages batch k+1's H2D transfer. Yields
    staged feeds in source order."""
    in_flight = 0
    for feed in batches:
        prefetcher.put(feed)
        in_flight += 1
        if in_flight >= 2:
            yield prefetcher.get()
            in_flight -= 1
    while in_flight:
        yield prefetcher.get()
        in_flight -= 1


# ---------------------------------------------------------------------------
# persistent compilation cache
# ---------------------------------------------------------------------------

_PERSISTENT = {"dir": None}


def setup_persistent_cache(cache_dir=None):
    """Point jax's on-disk compilation cache at `cache_dir` (default:
    $PTPU_CACHE_DIR). Idempotent, first configured dir wins; returns the
    active dir or None when unconfigured. With this set, a fresh process
    re-running the same program skips XLA recompiles entirely — the
    executable is deserialized from disk."""
    if _PERSISTENT["dir"]:
        return _PERSISTENT["dir"]
    from .flags import env as _env

    cache_dir = cache_dir or _env("PTPU_CACHE_DIR")
    if not cache_dir:
        return None
    import jax

    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # cache everything: the default thresholds skip small/fast compiles,
    # which is exactly the CPU-test regime the process-sim tests run in
    for opt, val in (("jax_persistent_cache_min_entry_size_bytes", -1),
                     ("jax_persistent_cache_min_compile_time_secs", 0.0)):
        try:
            jax.config.update(opt, val)
        except Exception:
            pass  # knob absent on this jax version
    try:
        # jax initializes its cache singleton lazily on the FIRST compile;
        # if anything compiled before this call (with no dir configured)
        # the disabled state is latched for the process — reset so the
        # new dir takes effect
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc)

        _cc.reset_cache()
    except Exception:
        pass
    _PERSISTENT["dir"] = cache_dir
    return cache_dir


def persistent_cache_dir():
    return _PERSISTENT["dir"]


def note_compiled_program(*fingerprint_parts):
    """Record a program-level compile in the persistent manifest under the
    framework's OWN cache key (program fingerprint + feed signature +
    fetch names + jax/jaxlib versions). Returns 'hit' when an earlier
    process (or executor) already compiled this exact key against the
    active cache dir — i.e. the jit compile below it is expected to be
    served from disk — else records it and returns 'miss'. None when no
    persistent cache is configured."""
    d = _PERSISTENT["dir"]
    if not d:
        return None
    import jax
    import jaxlib.version

    key = hashlib.sha256(repr(
        (jax.__version__, jaxlib.version.__version__, jax.default_backend(),
         fingerprint_parts)).encode()).hexdigest()
    mdir = os.path.join(d, "ptpu_manifest")
    path = os.path.join(mdir, key)
    if os.path.exists(path):
        _metrics.counter("compile_cache/persistent_hit").inc()
        return "hit"
    try:
        os.makedirs(mdir, exist_ok=True)
        with open(path, "w") as f:
            f.write("")
    except OSError:
        return None  # read-only cache dir: stay quiet, jax still reads
    _metrics.counter("compile_cache/persistent_miss").inc()
    return "miss"
