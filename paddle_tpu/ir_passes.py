"""Default compile-time program-optimization pipeline.

Parity: the reference's multi-device builder runs a graph-pass pipeline
before execution (framework/details/build_strategy.cc — the
`fuse_elewise_add_act_ops` / `memory_optimize` / `enable_inplace` knobs
all name real framework/ir/ passes). TPU-native, the pipeline runs at
COMPILE time on a clone of the program, right before the executor lowers
it into one jitted step: the passes shrink what gets traced into XLA
(trace time, StableHLO module size, compile latency) and drop
fetch-unreachable work from the steady-state step entirely.

Generic passes (registered in `paddle_tpu.ir`'s registry, composable with
user passes):

  constant_fold         evaluate const-only subgraphs once via the op
                        registry's own kernels; small results stay as
                        inline constants, large ones bake into the scope
                        as initialized parameters
  cse                   common-subexpression elimination (type + inputs
                        at identical reaching definitions + attrs)
  fuse_elewise_add_act  elementwise_add + {relu,tanh,sigmoid} ->
                        fused_elemwise_activation (BuildStrategy.
                        fuse_elewise_add_act_ops)
  fetch_dce             drop ops whose outputs cannot reach a fetch
                        target, persistable write, or side-effecting op
  conv_bn_fold_baked    non-destructive conv+bn fold for compile-time
                        clones: folded weights land in NEW scope entries,
                        the user's original parameters stay untouched

Entry points: `Executor.run` and `CompiledProgram._run` call
`optimize_for_execution` on every compile-cache miss; the cache key
carries `pipeline_key(...)` so BuildStrategy knobs and the opt-out are
part of the compiled-step identity. `PTPU_NO_PROGRAM_OPT=1` disables
everything and restores the exact unoptimized lowering path.

Every pass mutates ONLY the cloned program it is handed (constant folding
and conv_bn_fold_baked additionally write fresh, content-addressed
persistable entries into the scope — never existing names), so the
original program can keep executing unoptimized against the same scope.

Soundness invariants shared by the rewriting passes:
  - ops referenced (transitively) through a surviving op's `__fwd_op__`
    attr are never deleted — grad ops re-run their forward op's kernel
    and the serialized desc stores the reference by op index;
  - var names read by OTHER blocks (control-flow sub-blocks close over
    parent vars) are never rewired or orphaned;
  - CSE/folding only treat a var as value-stable when its name has a
    single static definition reaching every rewired read (reaching-def
    indices are part of the CSE key, so in-place rebinding is safe).
"""

import time

import numpy as np

from . import flags as _flags
from .observability import metrics as _metrics
from .observability import tracing as _tracing

__all__ = [
    "pipeline_enabled", "build_pipeline", "pipeline_key",
    "optimize_for_execution", "InplaceInfo", "program_is_inference",
]

# fused_elemwise_activation supports exactly these unary functors with
# impls identical to the standalone activation ops (bitwise-preserving)
_FUSABLE_ACTS = ("relu", "tanh", "sigmoid")

# constant folding refuses to bake results larger than this (a folded
# iota the size of an embedding table belongs in the program, not the
# scope)
_MAX_FOLD_BYTES = 1 << 24

# folded values up to this many elements stay INLINE (one assign_value
# op, lowered as a module-embedded constant): consumers that require
# trace-time-concrete values (tensor-array indices, static range bounds)
# keep working, exactly as they did with the original const op. Larger
# values bake as initialized scope parameters instead — they enter the
# step as arguments, keeping big constants out of the StableHLO module.
_INLINE_FOLD_ELEMS = 1 << 16

# pure-but-context-sensitive kernels (mesh/collective dependent): their
# compile-time evaluation context differs from the step's, so they never
# constant-fold; ditto any op carrying the __loss_seed__ attr, whose
# value scales by ctx.grad_seed_scale at lowering time
_CTX_SENSITIVE_TYPES = frozenset({"flash_attention"})

# donation promotion only pays off (and only risks an unused-donation
# warning) for buffers worth freeing early
_MIN_PROMOTE_BYTES = 1 << 20


def pipeline_enabled():
    """False under PTPU_NO_PROGRAM_OPT=1 — every compile-time transform
    (including donation promotion) gates on this, so the opt-out restores
    the exact unoptimized lowering path."""
    return not _flags.env("PTPU_NO_PROGRAM_OPT")


def program_is_inference(program):
    """True when the program carries no backward/optimizer ops and every
    train/eval-switchable op (dropout, batch_norm) is pinned to test mode
    — i.e. a clone(for_test=True)-shaped program. Cached per program
    mutation version (checked on the executor hot path)."""
    from .framework import Program, _TEST_MODE_OPS

    cached = getattr(program, "_is_test_cache", None)
    if cached is not None and cached[0] == program.version:
        return cached[1]
    result = True
    for blk in program.blocks:
        for op in blk.ops:
            if Program._is_train_only_op(op):
                result = False
                break
            if "is_test" in _TEST_MODE_OPS.get(op.type, ()) \
                    and not op.attrs.get("is_test", False):
                result = False
                break
        if not result:
            break
    program._is_test_cache = (program.version, result)
    return result


def _amp_cfg(build_strategy=None, program=None):
    """The AMP config in effect for one compile (None = inactive — the
    exact pre-AMP pipeline and cache keys). Importing amp here also
    guarantees the amp_rewrite pass is registered before the pipeline
    asks for it."""
    from . import amp

    return amp.active_config(program, build_strategy)


def _quant_cfg(build_strategy=None, program=None):
    """The quantization config in effect for one compile (None =
    inactive — the exact pre-quant pipeline and cache keys). The lazy
    import registers the quant_rewrite pass (docs/QUANTIZATION.md)."""
    from . import quant

    return quant.active_config(program, build_strategy)


def _embed_cfg(program=None):
    """The embedding-prefetch config in effect for one compile (None =
    inactive — the exact legacy host-lookup pipeline and cache keys).
    Decoration-only (a live HostEmbeddingPrefetcher, never a bare env
    flag); the lazy import registers the embed_prefetch_rewrite pass
    (docs/RECOMMENDER.md)."""
    if program is None or getattr(program, "_embed_config", None) is None:
        return None
    from .parallel import embedding_pipeline

    return embedding_pipeline.active_config(program)


def build_pipeline(build_strategy=None, is_test=False, infer_opt=False,
                   single_block=True, amp=False, quant=False,
                   embed=False):
    """Ordered pass-name list for one compile. `infer_opt` is the
    explicit inference-optimize request (with_inference_optimize /
    AnalysisConfig ir_optim) and adds the numerics-adjusting conv folds;
    `is_test` alone stays bitwise-preserving. `amp` (an active
    amp.AmpConfig resolved by the caller) adds the bf16 dtype rewrite
    ahead of constant_fold/cse so the inserted casts fold and dedup;
    `quant` (an active quant.QuantConfig) schedules the int8 rewrite in
    the same slot — after the conv folds (so quantization sees folded
    weights), before cse (so duplicate quantize/dequantize ops dedup)."""
    names = []
    if (is_test or infer_opt) and single_block:
        # identity at test time (downgrade dropout becomes the identical
        # x*(1-p) scale); the rename rewiring only covers one block
        names.append("dropout_remove")
    if infer_opt:
        names.append("conv_bn_fold_baked")
        names.append("conv_elementwise_add_fuse")
    if embed:
        # before amp/quant: the prefetch rewrite only rewires a lookup's
        # inputs (same f32 semantics), and the later passes then see the
        # final op type like any other gray op
        names.append("embed_prefetch_rewrite")
    if amp:
        names.append("amp_rewrite")
    if quant:
        names.append("quant_rewrite")
    names.append("constant_fold")
    names.append("cse")
    if infer_opt or (build_strategy is not None
                     and getattr(build_strategy,
                                 "fuse_elewise_add_act_ops", False)):
        names.append("fuse_elewise_add_act")
    names.append("fetch_dce")
    if build_strategy is not None and getattr(build_strategy,
                                              "memory_optimize", False):
        names.append("memory_optimize")
    return names


def pipeline_key(build_strategy=None, program=None, infer_opt=False):
    """Compile-cache key component covering the pass list and the
    BuildStrategy knobs that select it. Cheap enough for the per-run hot
    path (program inspection is cached on the program version)."""
    if not pipeline_enabled():
        return ("noopt",)
    is_test = program_is_inference(program) if program is not None else False
    single = program is None or program.num_blocks == 1
    amp_cfg = _amp_cfg(build_strategy, program)
    quant_cfg = _quant_cfg(build_strategy, program)
    embed_cfg = _embed_cfg(program)
    key = tuple(build_pipeline(build_strategy, is_test, infer_opt, single,
                               amp=amp_cfg is not None,
                               quant=quant_cfg is not None,
                               embed=embed_cfg is not None))
    if embed_cfg is not None:
        # attaching/detaching a HostEmbeddingPrefetcher (or changing its
        # cache geometry) must not reuse a step compiled the other way
        key += ("embed:" + embed_cfg.cache_key(),)
    if amp_cfg is not None:
        # flipping PTPU_AMP (or re-decorating with different lists) must
        # not reuse a compiled step rewritten under the other policy
        key += ("amp:" + amp_cfg.cache_key(),)
    if quant_cfg is not None:
        # same contract for PTPU_QUANT / quant.decorate: a step compiled
        # under one quantization policy can't serve another
        key += ("quant:" + quant_cfg.cache_key(),)
    if build_strategy is not None:
        # enable_inplace selects the donation classification of the
        # compiled step — flipping it must not reuse a stale entry
        key += ("inplace:%d" % int(getattr(build_strategy,
                                           "enable_inplace", True)),)
    from .ops.kernel_registry import cache_key as _kernel_cache_key

    kk = _kernel_cache_key()
    if kk != "auto":
        # PTPU_KERNELS selects both quant_rewrite's fused-op emission
        # and every trace-time kernel dispatch — a step compiled under
        # one mode must not serve another. The default (auto) state adds
        # nothing, keeping pre-kernel cache keys bitwise identical.
        key += ("kernels:" + kk,)
    return key


def optimize_for_execution(program, fetch_names, scope=None,
                           build_strategy=None, infer_opt=False):
    """Run the default pipeline on a CLONE of `program` and return the
    optimized clone (or the original, untouched, when the pipeline is
    disabled or changed nothing). Called on every compile-cache miss."""
    if not pipeline_enabled():
        return program
    amp_cfg = _amp_cfg(build_strategy, program)
    quant_cfg = _quant_cfg(build_strategy, program)
    embed_cfg = _embed_cfg(program)
    names = build_pipeline(build_strategy, program_is_inference(program),
                           infer_opt, program.num_blocks == 1,
                           amp=amp_cfg is not None,
                           quant=quant_cfg is not None,
                           embed=embed_cfg is not None)
    from .ir import get_pass

    clone = program.clone()
    clone._opt_fetch_targets = tuple(fetch_names)
    if amp_cfg is not None:
        # the clone is what the amp_rewrite pass sees — pin the resolved
        # config (decoration / BuildStrategy.amp / PTPU_AMP) on it
        clone._amp_config = amp_cfg
    if quant_cfg is not None:
        # ditto for the quant_rewrite pass (decoration / PTPU_QUANT)
        clone._quant_config = quant_cfg
    baked = getattr(program, "_baked_values", None)
    if baked:
        # re-optimizing an already-optimized program (e.g. the
        # with_inference_optimize non-dp path hands its clone to
        # Executor.run) must not lose the state_fallback values
        clone._baked_values = dict(baked)
    # PTPU_VERIFY_PASSES=1: verify the input clone, then re-verify after
    # every pass, blaming the pass that introduced a violation (docs/
    # STATIC_ANALYSIS.md). Env unset -> verifier is None and this path is
    # exactly the pre-verifier one.
    verifier = None
    from .analysis import verifier as _av

    if _av.verify_enabled():
        verifier = _av.PassPipelineVerifier(clone, tuple(fetch_names))
    rec = _metrics.enabled()
    changed_any = False
    for name in names:
        v0 = clone.version
        t0 = time.perf_counter()
        with _tracing.span("pass:" + name):
            get_pass(name).apply(clone, scope)
        if rec:
            _metrics.histogram("compiler/pass_time").observe(
                time.perf_counter() - t0)
        if verifier is not None:
            # unconditionally — a buggy pass that mutates WITHOUT
            # bumping the version must still be blamed
            verifier.after_pass(name, clone)
        changed_any = changed_any or clone.version != v0
    if not changed_any:
        # nothing fired: hand the executor the ORIGINAL program so the
        # common case keeps its exact pre-optimization identity
        return program
    if rec:
        _metrics.counter("compiler/programs_optimized").inc()
    return clone


# ---------------------------------------------------------------------------
# shared analyses
# ---------------------------------------------------------------------------


def _fetch_targets(program):
    """Fetch-target names the pipeline runner pinned on the clone; None
    means "unknown" and makes the fetch-driven passes no-ops (a user
    applying `fetch_dce` standalone must set program._opt_fetch_targets)."""
    return getattr(program, "_opt_fetch_targets", None)


def _outside_reads(program):
    """Var names read by any op OUTSIDE the global block (control-flow
    sub-blocks close over parent-block vars by name)."""
    gb = program.global_block()
    reads = set()
    for blk in program.blocks:
        if blk is gb:
            continue
        for op in blk.ops:
            reads.update(op.input_names())
    return reads


def _outside_writes(program):
    """Var names written by any op outside the global block: their write
    ORDER relative to global-block ops is unknown, so value-identity
    reasoning (CSE reaching defs, single-assignment checks) must treat
    them as unstable."""
    gb = program.global_block()
    writes = set()
    for blk in program.blocks:
        if blk is gb:
            continue
        for op in blk.ops:
            writes.update(op.output_names())
    return writes


def bake_value(program, name, value):
    """Record a compile-time-materialized value on the optimized program
    (baked folded constants, folded conv weights). `state_fallback`
    re-seeds any scope that lacks the entry, so a cached compiled step
    stays valid across scopes."""
    baked = getattr(program, "_baked_values", None)
    if baked is None:
        baked = program._baked_values = {}
    baked[name] = value


def state_fallback(program, inplace, name):
    """Value for a persistable step input missing from the run scope:
    baked compile-time constants come back verbatim; donation-promoted
    write-before-read names come back as zeros (their input value is
    dead — the step overwrites before any read). None = genuinely
    uninitialized."""
    baked = getattr(program, "_baked_values", None)
    if baked and name in baked:
        return baked[name]
    if inplace is not None and name in inplace.promoted:
        shape, dtype = inplace.promoted[name]
        return np.zeros(shape, dtype)
    return None


def _grad_referenced_ids(program):
    """ids of ops referenced (transitively) through `__fwd_op__` attrs —
    grad ops re-run these kernels and the serialized desc stores them by
    op index, so rewriting passes must not delete them."""
    from .framework import Operator

    refed = set()
    for blk in program.blocks:
        for op in blk.ops:
            fwd = op.attrs.get("__fwd_op__")
            while isinstance(fwd, Operator) and id(fwd) not in refed:
                refed.add(id(fwd))
                fwd = fwd.attrs.get("__fwd_op__")
    return refed


def _write_indices(block):
    """{name: [op index, ...]} for every output name in `block`."""
    writes = {}
    for i, op in enumerate(block.ops):
        for n in op.output_names():
            writes.setdefault(n, []).append(i)
    return writes


def _is_pure(op):
    """Pure program-level op: a registered, stateless kernel with no
    bespoke lowering, no structural role, no sub-block/operator attrs and
    no grad machinery — safe to evaluate, dedup or delete on the usual
    liveness grounds."""
    from .core.lowering import _SPECIAL, _STRUCTURAL
    from .framework import Block, Operator
    from .ops import registry

    if op.type in _STRUCTURAL or op.type in _SPECIAL:
        return False
    if "__fwd_op__" in op.attrs:
        return False
    if not registry.has(op.type) or registry.get(op.type).stateful:
        return False
    return not any(isinstance(v, (Block, Operator))
                   for v in op.attrs.values())


def _attr_fingerprint(attrs):
    """Hashable canonical form of an op's attrs (ndarrays by content,
    containers recursively)."""
    def canon(v):
        if isinstance(v, np.ndarray):
            return ("__ndarray__", v.shape, str(v.dtype), v.tobytes())
        if isinstance(v, (list, tuple)):
            return tuple(canon(x) for x in v)
        if isinstance(v, dict):
            return tuple(sorted((kk, canon(vv)) for kk, vv in v.items()))
        try:
            hash(v)
        except TypeError:
            return repr(v)
        return v

    return tuple((k, canon(attrs[k])) for k in sorted(attrs))


# ---------------------------------------------------------------------------
# inplace / last-use analysis -> donation classification
# ---------------------------------------------------------------------------


class InplaceInfo:
    """Donation policy handed to `compiler.classify_persistable_state`
    (BuildStrategy.enable_inplace made real). `enabled=False` moves every
    read+written persistable out of the donated set — buffers are never
    aliased in place, the scope's pre-step arrays stay valid (debugging
    parity with the reference's inplace pass off). `enabled=True` keeps
    the standard donation AND promotes write-before-read persistables
    (outputs whose old value no step op reads — e.g. a re-filled
    accumulator) into the donated inputs, so their stale scope buffers
    join XLA's arena for the step instead of pinning HBM; only buffers
    >= min_promote_bytes are worth the extra argument."""

    def __init__(self, enabled=True, scope=None,
                 min_promote_bytes=_MIN_PROMOTE_BYTES):
        self.enabled = enabled
        self.scope = scope
        self.min_promote_bytes = min_promote_bytes
        # name -> (shape, dtype) of promoted write-before-read inputs;
        # state_fallback synthesizes zeros from this when a later run
        # scope has no value (the input is dead — write precedes read)
        self.promoted = {}

    def adjust(self, block, state_in, state_out, mut, const):
        if not self.enabled:
            return [], const + mut
        if self.scope is None:
            return mut, const
        promoted = []
        for name in state_out:
            if name in state_in:
                continue
            val = self.scope.get(name)
            if val is None:
                continue
            nbytes = getattr(val, "nbytes", None)
            if nbytes is None:
                val = np.asarray(val)
                nbytes = val.nbytes
            if nbytes >= self.min_promote_bytes:
                promoted.append(name)
                dt = getattr(val, "dtype", None)
                self.promoted[name] = (tuple(np.shape(val)),
                                       dt if dt is not None
                                       else np.asarray(val).dtype)
        return mut + promoted, const


# ---------------------------------------------------------------------------
# the passes
# ---------------------------------------------------------------------------


def _register_builtin_passes():
    """Registered lazily from paddle_tpu.ir to keep a single import
    direction (ir -> ir_passes)."""
    from .ir import register_pass, Pass

    @register_pass("fetch_dce")
    class FetchDeadOpEliminationPass(Pass):
        """Fetch-driven dead-op elimination: remove global-block ops whose
        outputs cannot reach a fetch target, a persistable write, a
        side-effecting/structural op, a sub-block read, or a surviving
        grad op's forward reference. Name-based and order-insensitive,
        i.e. conservative under in-place rebinding."""

        def apply(self, program, scope=None):
            from .core.lowering import _SPECIAL, _STRUCTURAL
            from .framework import Block, Operator

            targets = _fetch_targets(program)
            if targets is None:
                return program
            block = program.global_block()
            ops = block.ops
            idx_of = {id(op): i for i, op in enumerate(ops)}
            writers = _write_indices(block)

            live = set()
            live_names = set(targets) | _outside_reads(program)
            for i, op in enumerate(ops):
                anchor = (op.type in _STRUCTURAL or op.type in _SPECIAL
                          or not op.output_names()
                          or any(isinstance(v, Block)
                                 for v in op.attrs.values()))
                if not anchor:
                    for n in op.output_names():
                        v = block._find_var_recursive(n)
                        if v is not None and v.persistable:
                            anchor = True
                            break
                if anchor:
                    live.add(i)

            changed = True
            while changed:
                changed = False
                for n in list(live_names):
                    for i in writers.get(n, ()):
                        if i not in live:
                            live.add(i)
                            changed = True
                for i in list(live):
                    op = ops[i]
                    new = set(op.input_names()) - live_names
                    if new:
                        live_names |= new
                        changed = True
                    fwd = op.attrs.get("__fwd_op__")
                    while isinstance(fwd, Operator):
                        j = idx_of.get(id(fwd))
                        if j is not None and j not in live:
                            live.add(j)
                            changed = True
                        fwd = fwd.attrs.get("__fwd_op__")

            if len(live) == len(ops):
                return program
            removed = len(ops) - len(live)
            block.ops = [op for i, op in enumerate(ops) if i in live]
            _metrics.counter("compiler/ops_removed").inc(removed)
            program._bump_version()
            return program

    @register_pass("cse")
    class CommonSubexpressionEliminationPass(Pass):
        """Dedup pure global-block ops computing the identical value: the
        key is (type, per-slot inputs as (name, reaching-def index),
        output arity, attrs). A later duplicate is deleted and every
        subsequent reader rewired to the kept op's outputs; outputs that
        are fetched, persistable, multiply-written, or read by sub-blocks
        stay put."""

        def apply(self, program, scope=None):
            targets = _fetch_targets(program)
            if targets is None:
                # fetch set unknown: eliminating an op could orphan a
                # name the caller intends to fetch (the documented
                # _fetch_targets contract — pin program._opt_fetch_targets
                # to run this pass standalone)
                return program
            block = program.global_block()
            protected = set(targets) | _outside_reads(program)
            grad_refed = _grad_referenced_ids(program)
            writes = _write_indices(block)
            # names also written by sub-block ops: their write order
            # relative to global ops is unknown — no stable reaching def
            sub_written = _outside_writes(program)

            def rdef(name, i):
                if name in sub_written:
                    return None
                last = -1
                for w in writes.get(name, ()):
                    if w < i:
                        last = w
                    else:
                        break
                return last

            seen = {}
            rewire = {}
            removed = []
            for i, op in enumerate(block.ops):
                for slot, vs in op.inputs.items():
                    op.inputs[slot] = [rewire.get(v.name, v) for v in vs]
                if not _is_pure(op):
                    continue
                key_in = []
                ok = True
                for slot in sorted(op.inputs):
                    ids = []
                    for v in op.inputs[slot]:
                        d = rdef(v.name, i)
                        if d is None:
                            ok = False
                            break
                        ids.append((v.name, d))
                    if not ok:
                        break
                    key_in.append((slot, tuple(ids)))
                if not ok:
                    continue
                key = (op.type, tuple(key_in),
                       tuple(sorted((s, len(vs))
                                    for s, vs in op.outputs.items())),
                       _attr_fingerprint(op.attrs))
                kept = seen.get(key)
                if kept is None:
                    seen[key] = op
                    continue
                eliminable = id(op) not in grad_refed
                for n in op.output_names():
                    v = block._find_var_recursive(n)
                    if (n in protected or n in sub_written
                            or len(writes.get(n, ())) != 1
                            or (v is not None
                                and (v.persistable or v.is_data))):
                        eliminable = False
                        break
                # the KEPT op's outputs must be singly-written too: a
                # later in-place rebinding of the kept name would make
                # rewired readers observe the REBOUND value, not the
                # common subexpression
                for n in kept.output_names():
                    if n in sub_written or len(writes.get(n, ())) != 1:
                        eliminable = False
                        break
                if not eliminable:
                    continue
                for slot, vs in op.outputs.items():
                    for v, kv in zip(vs, kept.outputs.get(slot, ())):
                        rewire[v.name] = kv
                removed.append(i)
            if removed:
                gone = set(removed)
                block.ops = [op for i, op in enumerate(block.ops)
                             if i not in gone]
                _metrics.counter("compiler/ops_removed").inc(len(removed))
                program._bump_version()
            return program

    @register_pass("constant_fold")
    class ConstantFoldPass(Pass):
        """Evaluate const-only subgraphs once at compile time through the
        op registry's own kernels and bake each boundary value into the
        scope as an initialized parameter (a fresh content-addressed
        persistable var — existing names are never overwritten, so the
        unoptimized program keeps running against the same scope). The
        dead const producers are swept by the fetch_dce pass behind it."""

        def apply(self, program, scope=None):
            if scope is None:
                return program
            import hashlib

            import jax

            from .core.lowering import LoweringContext
            from .ops import registry

            targets = set(_fetch_targets(program) or ())
            block = program.global_block()
            outside = _outside_reads(program)
            grad_refed = _grad_referenced_ids(program)
            writes = _write_indices(block)
            sub_written = _outside_writes(program)

            ctx = LoweringContext(base_key=jax.random.PRNGKey(0))
            const_vals = {}
            const_ops = set()
            for op in block.ops:
                if not _is_pure(op) or id(op) in grad_refed:
                    continue
                if op.type in _CTX_SENSITIVE_TYPES \
                        or "__loss_seed__" in op.attrs:
                    continue
                names_in = op.input_names()
                if any(n not in const_vals for n in names_in):
                    continue
                foldable = True
                for n in op.output_names():
                    v = block._find_var_recursive(n)
                    if (n in sub_written or len(writes.get(n, ())) != 1
                            or v is None or v.persistable or v.is_data):
                        foldable = False
                        break
                if not foldable:
                    continue
                ins = {slot: [const_vals[v.name] for v in vs]
                       for slot, vs in op.inputs.items() if vs}
                try:
                    with _tracing.span("fold:" + op.type):
                        outs = registry.get(op.type).impl(ctx, ins,
                                                          op.attrs)
                except Exception:
                    continue
                vals = {}
                for slot, vs in op.outputs.items():
                    produced = outs.get(slot)
                    if produced is None:
                        continue
                    for v, val in zip(vs, produced):
                        arr = np.asarray(val)
                        if arr.nbytes > _MAX_FOLD_BYTES:
                            vals = None
                            break
                        vals[v.name] = arr
                    if vals is None:
                        break
                if vals is None:
                    continue
                const_vals.update(vals)
                const_ops.add(id(op))
            if not const_ops:
                return program

            from .framework import Operator

            # boundary values: const vars read by a non-const op. Small
            # ones become ONE inline assign_value producing the SAME var
            # (a module-embedded constant — consumers needing trace-time
            # concreteness keep it, no rewiring); big ones bake as fresh
            # persistable scope params and the readers are rewired.
            boundary = set()
            for op in block.ops:
                if id(op) in const_ops:
                    continue
                boundary.update(n for n in op.input_names()
                                if n in const_vals)
            boundary |= {n for n in const_vals if n in outside}

            producer = {}
            for op in block.ops:
                if id(op) in const_ops:
                    for n in op.output_names():
                        producer[n] = op

            changed = False
            baked = {}
            for name in sorted(boundary):
                arr = const_vals[name]
                prod = producer[name]
                if arr.size <= _INLINE_FOLD_ELEMS:
                    if len(prod.output_names()) != 1:
                        continue  # multi-output producer: leave it be
                    if prod.type == "assign_value":
                        # already the folded form (idempotence: a
                        # re-optimized program must not read as changed)
                        continue
                    v = block.var(name)
                    # dtype = the EVALUATED dtype: the eager evaluation
                    # already applied jax's canonicalization (int64 ->
                    # int32 under x64-off), so lowering re-materializes
                    # the value with NO conversion — conversions on this
                    # jax stage a traced op, and consumers needing a
                    # trace-time-concrete value (tensor-array indices)
                    # would break
                    block.ops[block.ops.index(prod)] = Operator(
                        block, "assign_value", inputs={},
                        outputs={"Out": [v]},
                        attrs={"shape": list(arr.shape),
                               "dtype": str(arr.dtype), "values": arr})
                    const_ops.discard(id(prod))
                    changed = True
                elif name not in outside and name not in targets:
                    digest = hashlib.sha1(
                        arr.tobytes() + repr((name, arr.shape,
                                              str(arr.dtype))).encode()
                    ).hexdigest()[:12]
                    fname = "__folded__.%s.%s" % (digest, name)
                    if not block.has_var(fname):
                        block.create_var(name=fname, shape=arr.shape,
                                         dtype=block.var(name).dtype,
                                         persistable=True)
                    scope.set(fname, np.asarray(arr))
                    # a cached step may later run against a DIFFERENT
                    # scope: keep the value on the program so the state
                    # read can re-seed it (state_fallback)
                    bake_value(program, fname, np.asarray(arr))
                    baked[name] = block.var(fname)
                    changed = True
            if baked:
                for op in block.ops:
                    if id(op) in const_ops:
                        continue
                    for slot, vs in op.inputs.items():
                        op.inputs[slot] = [baked.get(v.name, v)
                                           for v in vs]
            if changed:
                _metrics.counter("compiler/ops_folded").inc(
                    len(const_ops))
                program._bump_version()
            return program

    @register_pass("fuse_elewise_add_act")
    class FuseElewiseAddActPass(Pass):
        """elementwise_add -> {relu,tanh,sigmoid} (single consumer) ->
        one fused_elemwise_activation op — BuildStrategy.
        fuse_elewise_add_act_ops (fuse_elewise_add_act_pass.cc parity).
        Only trailing-broadcast adds fuse (the fused kernel applies numpy
        broadcasting; Fluid's axis must agree) and the standalone act
        impls are bitwise-identical to the fused functors."""

        def apply(self, program, scope=None):
            from .framework import Operator

            targets = set(_fetch_targets(program) or ())
            block = program.global_block()
            protected = (targets | _outside_reads(program)
                         | _outside_writes(program))
            grad_refed = _grad_referenced_ids(program)
            writes = _write_indices(block)
            consumers = {}
            for op in block.ops:
                for n in set(op.input_names()):
                    consumers.setdefault(n, []).append(op)

            def _trailing_broadcast(add):
                xs, ys = add.inputs.get("X", []), add.inputs.get("Y", [])
                if len(xs) != 1 or len(ys) != 1:
                    return False
                axis = add.attrs.get("axis", -1)
                if axis in (-1, None):
                    return True
                xsh = getattr(xs[0], "shape", None)
                ysh = getattr(ys[0], "shape", None)
                if xsh is None or ysh is None:
                    return False
                return axis == len(xsh) - len(ysh)

            fused = 0
            new_ops = list(block.ops)
            for add in block.ops:
                if add.type != "elementwise_add" or add not in new_ops:
                    continue
                if id(add) in grad_refed or not _trailing_broadcast(add):
                    continue
                outs = add.output_names("Out")
                if len(outs) != 1 or outs[0] in protected \
                        or len(writes.get(outs[0], ())) != 1:
                    continue
                users = consumers.get(outs[0], [])
                if len(users) != 1 or users[0] not in new_ops:
                    continue
                act = users[0]
                if act.type not in _FUSABLE_ACTS or id(act) in grad_refed:
                    continue
                if act.attrs or act.input_names() != outs:
                    continue
                act_outs = act.output_names("Out")
                if len(act_outs) != 1 \
                        or len(writes.get(act_outs[0], ())) != 1:
                    continue  # rebinding: moving the def earlier unsafe
                fop = Operator(
                    block, "fused_elemwise_activation",
                    inputs={"X": add.inputs["X"], "Y": add.inputs["Y"]},
                    outputs={"Out": act.outputs["Out"],
                             "IntermediateOut": add.outputs["Out"]},
                    attrs={"functor_list": [act.type, "elementwise_add"],
                           "save_intermediate_out": False})
                new_ops[new_ops.index(add)] = fop
                new_ops.remove(act)
                fused += 1
            if fused:
                block.ops = new_ops
                _metrics.counter("compiler/ops_fused").inc(fused)
                program._bump_version()
            return program

    @register_pass("conv_bn_fold_baked")
    class ConvBNFoldBakedPass(Pass):
        """conv2d -> batch_norm(is_test) fold for compile-time clones:
        same algebra as the `conv_bn_fold` builtin but NON-destructive —
        folded weights/bias land in fresh content-addressed scope entries
        and the conv is rewired to them, so the original program (which
        still carries the bn op) keeps reading its untouched parameters."""

        def apply(self, program, scope=None):
            if scope is None:
                return program
            import hashlib

            from .ir import match_chain

            block = program.global_block()
            protected = set(_fetch_targets(program) or ()) \
                | _outside_reads(program)
            changed = False
            for conv, bn in match_chain(block, ("conv2d", "batch_norm")):
                if not bn.attrs.get("is_test", False):
                    continue
                if any(n in protected for n in conv.output_names()):
                    # the pre-bn conv output is fetched (or read by a
                    # sub-block): rewiring it onto bn's Y would orphan
                    # the name — match_chain only counts consuming OPS
                    continue
                w_name = conv.input_names("Filter")[0]
                names = [w_name, bn.input_names("Scale")[0],
                         bn.input_names("Bias")[0],
                         bn.input_names("Mean")[0],
                         bn.input_names("Variance")[0]]
                vals = [scope.get(n) for n in names]
                if any(v is None for v in vals):
                    continue
                w, gamma, beta, mean, var = [np.asarray(v) for v in vals]
                eps = bn.attrs.get("epsilon", 1e-5)
                factor = gamma / np.sqrt(var + eps)
                w2 = (w * factor.reshape((-1, 1, 1, 1))).astype(w.dtype)
                shift = (beta - mean * factor).astype(w.dtype)
                digest = hashlib.sha1(
                    w2.tobytes() + shift.tobytes()).hexdigest()[:12]
                wf_name = "%s.bnfold.%s" % (w_name, digest)
                bf_name = "%s.bnfold_bias.%s" % (w_name, digest)
                if not block.has_var(wf_name):
                    block.create_var(name=wf_name, shape=w2.shape,
                                     dtype=str(w.dtype), persistable=True)
                if not block.has_var(bf_name):
                    block.create_var(name=bf_name, shape=shift.shape,
                                     dtype=str(shift.dtype),
                                     persistable=True)
                scope.set(wf_name, w2)
                scope.set(bf_name, shift)
                bake_value(program, wf_name, w2)
                bake_value(program, bf_name, shift)
                conv.inputs["Filter"] = [block.var(wf_name)]
                conv.inputs["FoldedBias"] = [block.var(bf_name)]
                conv.outputs["Output"] = bn.outputs["Y"]
                block.ops.remove(bn)
                _metrics.counter("compiler/ops_fused").inc()
                changed = True
            if changed:
                program._bump_version()
            return program

    return True


_register_builtin_passes()
