"""Live observability endpoint: /metrics, /healthz, /varz over stdlib
http.server.

The scrape surface the ROADMAP's autoscaling controller consumes: the
`serving/*` + `router/*` gauges must be readable WHILE the fleet runs,
not only from the atexit JSON dump. Off by default; set
`PTPU_METRICS_PORT=<port>` (0 = ephemeral) and the observability
package starts one daemon ThreadingHTTPServer bound to loopback at
import. No flag, no thread — the defaults-off identity the whole
telemetry layer keeps.

Routes:
  /metrics  Prometheus text 0.0.4 — exactly `registry().to_prometheus()`
            (CI's obs stage gates scrape==registry parity).
  /healthz  JSON snapshot of every registered health provider (the
            router registers replica states, each engine its worker
            `health()`); HTTP 503 when any provider reports or raises
            a failure, 200 otherwise.
  /varz     the full registry as JSON — `registry().to_dict()`, the
            same schema as dump_json/tools/ptpu_stats.py.

Health providers are registered only while the endpoint is enabled, so
a flag-off run never grows the provider dict (and never pins engines
live through it).
"""

import json
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import metrics as _metrics

__all__ = ["enabled", "start", "stop", "port", "url",
           "register_health_provider", "unregister_health_provider",
           "health_snapshot"]


def _make_lock(name):
    """Tracked when the concurrency tracker is loaded; passive import
    (metrics.py's bootstrap rationale)."""
    conc = sys.modules.get("paddle_tpu.analysis.concurrency")
    if conc is None:
        return threading.Lock()
    return conc.make_lock(name)


_server = None
_thread = None
_providers = {}  # name -> zero-arg callable returning a JSON-able dict
_providers_lock = threading.Lock()  # replaced by a tracked lock in start


def enabled():
    """True when the endpoint is running or flag-configured to run."""
    if _server is not None:
        return True
    from .. import flags as _flags

    return _flags.env("PTPU_METRICS_PORT") is not None


def register_health_provider(name, fn):
    """Expose `fn()`'s dict under /healthz key `name` (engines/routers
    call this at construction when the endpoint is enabled). Last
    registration per name wins — a restarted engine replaces its
    predecessor's snapshot."""
    with _providers_lock:
        _providers[name] = fn


def unregister_health_provider(name):
    with _providers_lock:
        _providers.pop(name, None)


def health_snapshot():
    """(http_status, doc): every provider's report, with a top-level
    "status" of ok/degraded. A provider raising is itself a health
    signal (a dead engine's lock may be poisoned) — recorded as its
    error string, never propagated into the serving thread."""
    with _providers_lock:
        providers = dict(_providers)
    doc = {"status": "ok", "providers": {}}
    status = 200
    for name, fn in sorted(providers.items()):
        try:
            doc["providers"][name] = fn()
        except Exception as e:  # noqa: BLE001 — scrape must not die
            doc["providers"][name] = {"error": str(e)}
            doc["status"] = "degraded"
            status = 503
    return status, doc


class _Handler(BaseHTTPRequestHandler):
    server_version = "ptpu-obs"

    def log_message(self, fmt, *args):  # no stderr chatter per scrape
        pass

    def _reply(self, status, content_type, body):
        data = body.encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 — http.server's required spelling
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                self._reply(200, "text/plain; version=0.0.4",
                            _metrics.to_prometheus())
            elif path == "/varz":
                self._reply(200, "application/json",
                            json.dumps(_metrics.registry().to_dict(),
                                       sort_keys=True))
            elif path == "/healthz":
                status, doc = health_snapshot()
                self._reply(status, "application/json",
                            json.dumps(doc, sort_keys=True))
            else:
                self._reply(404, "text/plain",
                            "unknown route %s (try /metrics, /healthz, "
                            "/varz)\n" % path)
        except Exception as e:  # noqa: BLE001 — a scrape bug must not
            # kill the server thread; surface it to the scraper instead
            try:
                self._reply(500, "text/plain", "scrape error: %s\n" % e)
            except OSError:
                pass


def start(port=None, host="127.0.0.1"):
    """Start the endpoint thread (idempotent; returns the bound port).
    `port=None` reads PTPU_METRICS_PORT; port 0 binds an ephemeral port
    readable back through `port()`."""
    global _server, _thread, _providers_lock
    if _server is not None:
        return _server.server_address[1]
    if port is None:
        from .. import flags as _flags

        port = _flags.env("PTPU_METRICS_PORT")
        if port is None:
            raise ValueError(
                "endpoint.start() needs a port (PTPU_METRICS_PORT unset)")
    _providers_lock = _make_lock("obs.endpoint")
    _server = ThreadingHTTPServer((host, int(port)), _Handler)
    _server.daemon_threads = True
    _thread = threading.Thread(target=_server.serve_forever,
                               name="ptpu-metrics-endpoint", daemon=True)
    _thread.start()
    return _server.server_address[1]


def stop():
    """Shut the endpoint down and join its thread (tests; production
    runs just let the daemon thread die with the process)."""
    global _server, _thread
    if _server is None:
        return
    _server.shutdown()
    _server.server_close()
    _thread.join(timeout=10)
    _server = None
    _thread = None


def port():
    """The bound port, or None when not running."""
    return _server.server_address[1] if _server is not None else None


def url(route="/metrics"):
    """http://127.0.0.1:<port><route>, or None when not running."""
    p = port()
    return None if p is None else "http://127.0.0.1:%d%s" % (p, route)
