"""Process-wide metrics registry (counters, gauges, histograms).

The measurement substrate every perf PR reports through (ROADMAP north
star: before/after numbers come from the framework itself, not ad-hoc
timers). Exposition formats: a JSON dump (`MetricsRegistry.to_dict` /
`dump_json`, rendered by tools/ptpu_stats.py) and Prometheus text
(`to_prometheus`) for scrape-style deployments of native_serve hosts.

Enablement contract: telemetry is OFF unless `PTPU_METRICS` is set (or
`enable()` is called), and the disabled path is a no-op fast path — the
module-level `counter()/gauge()/histogram()` helpers hand back shared
null singletons, so instrumented hot loops allocate nothing per step.
Registry objects themselves are always live: going through
`registry()` directly (bench.py --metrics-out does, so its results
share the dump with any executor telemetry) or constructing a private
`MetricsRegistry()` bypasses the global switch — explicit use IS the
opt-in.

Threading: one lock per registry guards name->metric creation, and each
metric guards its own mutation — `x += n` is a load/add/store sequence
the GIL can interleave, so counters shared across threads (the reader
thread and the main loop both live in one process) would drop updates
without it.
"""

import json
import math
import sys
import threading

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "registry", "counter", "gauge", "histogram", "enabled",
           "enable", "disable", "dump_json", "to_prometheus", "reset"]

# default histogram bucket upper bounds, in seconds: 100us .. ~100s
# exponential — wide enough for step times on one chip and compile times
DEFAULT_BUCKETS = (1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 1e-1, 3e-1,
                   1.0, 3.0, 10.0, 30.0, 100.0)


def _make_lock(name):
    """Named lock site (docs/STATIC_ANALYSIS.md): tracked under
    PTPU_LOCK_CHECK=1. STRICTLY passive about the import: this module
    executes during package bootstrap, and importing
    `paddle_tpu.analysis` from here would run `analysis.meta`'s
    kernel-conditional `declare(...)` calls against a half-registered op
    corpus (their registrations silently no-op — a measured breakage).
    Locks created before the analysis package exists (the global
    registry's own lock) stay plain; every metric lock created at
    runtime goes through the tracker."""
    conc = sys.modules.get("paddle_tpu.analysis.concurrency")
    if conc is None:
        return threading.Lock()
    return conc.make_lock(name)


class Counter:
    """Monotonically increasing count (Prometheus counter semantics)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name):
        self.name = name
        self._value = 0
        self._lock = _make_lock("obs.metric")

    def inc(self, n=1):
        if n < 0:
            raise ValueError("counter %r cannot decrease" % self.name)
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value

    def to_dict(self):
        return self._value


class Gauge:
    """Last-set value (queue depth, module bytes, throughput)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name):
        self.name = name
        self._value = 0.0
        self._lock = _make_lock("obs.metric")

    def set(self, v):
        self._value = float(v)

    def inc(self, n=1):
        with self._lock:
            self._value += n

    def dec(self, n=1):
        with self._lock:
            self._value -= n

    @property
    def value(self):
        return self._value

    def to_dict(self):
        return self._value


class Histogram:
    """Cumulative-bucket histogram with count/sum/min/max.

    min is float('inf') while empty — renderers must print a placeholder
    for zero-observation histograms rather than leak the sentinel (the
    legacy profiler table bug this layer fixes)."""

    __slots__ = ("name", "buckets", "bucket_counts", "count", "sum",
                 "min", "max", "_lock")

    def __init__(self, name, buckets=None):
        self.name = name
        # float-normalized so the JSON bucket keys (repr of each bound)
        # round-trip through tools/ptpu_stats.py --prometheus even when
        # the caller passed integer bounds
        self.buckets = tuple(sorted(float(b)
                                    for b in (buckets or DEFAULT_BUCKETS)))
        self.bucket_counts = [0] * (len(self.buckets) + 1)  # +Inf tail
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self._lock = _make_lock("obs.metric")

    def observe(self, v):
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v
            for i, le in enumerate(self.buckets):
                if v <= le:
                    self.bucket_counts[i] += 1
                    return
            self.bucket_counts[-1] += 1

    @property
    def avg(self):
        return self.sum / self.count if self.count else 0.0

    def quantile(self, q):
        """Bucket-interpolated quantile estimate (Prometheus
        histogram_quantile semantics: linear interpolation inside the
        bucket holding the q-th observation), clamped to the observed
        min/max so a wide first/last bucket cannot report a value outside
        the real range. The one shared percentile implementation — the
        serving engine's p50/p99 gauges read this, replacing its retired
        ad-hoc deque(1024) windows."""
        if not 0.0 <= q <= 1.0:
            raise ValueError("quantile %r outside [0, 1]" % (q,))
        if not self.count:
            return 0.0
        target = q * self.count
        cum = 0
        lo = 0.0
        for i, le in enumerate(self.buckets):
            n = self.bucket_counts[i]
            if n and cum + n >= target:
                v = lo + (le - lo) * (max(target - cum, 0.0) / n)
                return min(max(v, self.min), self.max)
            cum += n
            lo = le
        return self.max  # mass in the +Inf tail: best estimate is max

    def to_dict(self):
        d = {"count": self.count, "sum": self.sum, "avg": self.avg}
        if self.count:
            d["min"] = self.min
            d["max"] = self.max
            d["p50"] = self.quantile(0.50)
            d["p95"] = self.quantile(0.95)
            d["p99"] = self.quantile(0.99)
        return d | {"buckets": {
            ("+Inf" if i == len(self.buckets) else repr(self.buckets[i])): n
            for i, n in enumerate(self.bucket_counts)}}


class _NullMetric:
    """Shared no-op stand-in for every metric kind when telemetry is off:
    the instrumented call sites stay branch-free and allocation-free."""

    __slots__ = ()

    def inc(self, n=1):
        pass

    def dec(self, n=1):
        pass

    def set(self, v):
        pass

    def observe(self, v):
        pass

    @property
    def value(self):
        return 0


NULL_METRIC = _NullMetric()


class MetricsRegistry:
    """Name -> metric store. Names are slash-scoped ('executor/step_time');
    get-or-create, with a kind-conflict check so 'executor/step_time' can't
    be a counter in one file and a histogram in another."""

    def __init__(self):
        self._metrics = {}
        self._lock = _make_lock("obs.registry")

    def _get(self, name, cls, *args):
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.get(name)
                if m is None:
                    m = cls(name, *args)
                    self._metrics[name] = m
        if not isinstance(m, cls):
            raise TypeError("metric %r is a %s, not a %s"
                            % (name, type(m).__name__, cls.__name__))
        return m

    def counter(self, name):
        return self._get(name, Counter)

    def gauge(self, name):
        return self._get(name, Gauge)

    def histogram(self, name, buckets=None):
        if buckets is None:
            return self._get(name, Histogram)
        h = self._get(name, Histogram, buckets)
        if h.buckets != tuple(sorted(float(b) for b in buckets)):
            # a silent first-creation-wins would park every observation
            # in one bucket of the wrong scale; fail like kind conflicts
            raise ValueError(
                "histogram %r already exists with buckets %r"
                % (name, h.buckets))
        return h

    def metrics(self):
        with self._lock:
            return dict(self._metrics)

    def reset(self):
        with self._lock:
            self._metrics.clear()

    def to_dict(self):
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for name, m in sorted(self.metrics().items()):
            kind = ("counters" if isinstance(m, Counter) else
                    "gauges" if isinstance(m, Gauge) else "histograms")
            out[kind][name] = m.to_dict()
        return out

    def dump_json(self, path):
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)
        return path

    def to_prometheus(self, prefix="ptpu_"):
        """Prometheus text exposition format 0.0.4."""
        lines = []
        seen = {}  # mangled family name -> original metric name
        for name, m in sorted(self.metrics().items()):
            pn = prefix + _prom_name(name)
            other = seen.setdefault(pn, name)
            if other != name:
                # 'a/b' and 'a_b' both mangle to ptpu_a_b — merging them
                # silently would corrupt both series; fail like the
                # registry's kind-conflict check does
                raise ValueError(
                    "prometheus name collision: metrics %r and %r both "
                    "expose as %r" % (other, name, pn))
            if isinstance(m, Counter):
                lines.append("# TYPE %s_total counter" % pn)
                lines.append("%s_total %s" % (pn, _prom_num(m.value)))
            elif isinstance(m, Gauge):
                lines.append("# TYPE %s gauge" % pn)
                lines.append("%s %s" % (pn, _prom_num(m.value)))
            else:
                lines.append("# TYPE %s histogram" % pn)
                cum = 0
                for le, n in zip(m.buckets, m.bucket_counts):
                    cum += n
                    lines.append('%s_bucket{le="%s"} %d'
                                 % (pn, _prom_num(le), cum))
                lines.append('%s_bucket{le="+Inf"} %d' % (pn, m.count))
                lines.append("%s_sum %s" % (pn, _prom_num(m.sum)))
                lines.append("%s_count %d" % (pn, m.count))
        return "\n".join(lines) + ("\n" if lines else "")


def _prom_name(name):
    return "".join(c if (c.isalnum() or c == "_") else "_" for c in name)


def _prom_num(v):
    if isinstance(v, float):
        if math.isnan(v):
            return "NaN"  # int(nan) raises — a poisoned gauge must not
            # crash the scrape; ptpu_stats' NaN-hardened asserts catch it
        if math.isinf(v):
            return "+Inf" if v > 0 else "-Inf"
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
    return repr(v)


# ---------------------------------------------------------------------------
# process-wide default registry + enablement switch
# ---------------------------------------------------------------------------

_REGISTRY = MetricsRegistry()


def _env_on(name):
    """PTPU_* switch check through the central flags registry (bool flags
    parse with the shared spellings; path-valued flags count as on when
    set non-empty)."""
    from .. import flags as _flags

    return bool(_flags.env(name))


_ENABLED = _env_on("PTPU_METRICS")


def enabled():
    """One-branch check instrumented hot paths gate on."""
    return _ENABLED


def enable():
    global _ENABLED
    _ENABLED = True


def disable():
    global _ENABLED
    _ENABLED = False


def registry():
    """The process-wide registry (live even when disabled — explicit use
    is an opt-in, the switch only mutes the instrumented hot paths)."""
    return _REGISTRY


def counter(name):
    return _REGISTRY.counter(name) if _ENABLED else NULL_METRIC


def gauge(name):
    return _REGISTRY.gauge(name) if _ENABLED else NULL_METRIC


def histogram(name, buckets=None):
    return _REGISTRY.histogram(name, buckets) if _ENABLED else NULL_METRIC


def dump_json(path):
    return _REGISTRY.dump_json(path)


def to_prometheus():
    return _REGISTRY.to_prometheus()


def reset():
    _REGISTRY.reset()
