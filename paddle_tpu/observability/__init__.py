"""Unified telemetry layer: metrics registry + span tracing.

The measurement substrate for the whole framework (see
docs/OBSERVABILITY.md). Two sub-facilities, individually switchable:

  metrics  — process-wide counters/gauges/histograms with JSON and
             Prometheus exposition. Enable with PTPU_METRICS=1; set
             PTPU_METRICS_OUT=<path> to dump JSON at process exit.
  tracing  — nestable host spans exported as Chrome-trace/Perfetto
             JSON, forwarded to jax.profiler.TraceAnnotation (device
             XPlane alignment) and the native C++ collector. Enable
             with PTPU_TRACE=1, or PTPU_TRACE_DIR=<dir> to also write
             <dir>/ptpu_trace.json at process exit.

Instrumented hot paths (Executor.run per-step wall time + feed/fetch
bytes, the compiled-program cache, program lowering, PyReader's feed
queue) check one module-level bool and touch shared null objects when
telemetry is off — the disabled path allocates nothing per step.

The legacy `paddle_tpu.profiler` event table is a facade over this
registry since the telemetry PR; prefer these APIs in new code.
"""

import atexit
import os
import time

from . import flight_recorder, metrics, tracing
from .metrics import (Counter, Gauge, Histogram,  # noqa: F401
                      MetricsRegistry, counter, gauge, histogram, registry)
from .tracing import span  # noqa: F401

__all__ = ["metrics", "tracing", "flight_recorder", "span", "counter",
           "gauge", "histogram", "registry", "enabled", "enable",
           "disable", "dump_metrics", "dump_chrome_trace", "Counter",
           "Gauge", "Histogram", "MetricsRegistry"]


def enabled():
    """True when any telemetry facility is on."""
    return metrics.enabled() or tracing.enabled()


def enable():
    """Turn on both metrics and tracing (programmatic alternative to
    PTPU_METRICS=1 PTPU_TRACE=1)."""
    metrics.enable()
    tracing.enable()


def disable():
    metrics.disable()
    tracing.disable()


def dump_metrics(path):
    """Write the process-wide registry as JSON (tools/ptpu_stats.py
    renders it)."""
    return metrics.dump_json(path)


def dump_chrome_trace(path):
    """Write collected spans as Chrome-trace JSON (open in Perfetto)."""
    return tracing.dump_chrome_trace(path)


class _StepScope:
    """One executor step's shared instrumentation: a `step` span plus the
    executor/step_time histogram and executor/steps counter — used by
    both Executor.run and CompiledProgram._run so the two paths cannot
    drift. step_time is recorded only on clean exit (an op raising
    mid-step would otherwise pollute the latency distribution)."""

    __slots__ = ("_rec", "_span", "_t0")

    def __enter__(self):
        self._rec = metrics.enabled()
        self._span = tracing.span("step")
        self._span.__enter__()
        self._t0 = time.perf_counter() if self._rec else 0.0
        return self

    def __exit__(self, *exc):
        self._span.__exit__(*exc)
        if self._rec and exc[0] is None:
            reg = metrics.registry()
            reg.histogram("executor/step_time").observe(
                time.perf_counter() - self._t0)
            reg.counter("executor/steps").inc()
        return False


def step_scope():
    """Context manager instrumenting one executor step; the shared
    no-op singleton when telemetry is fully disabled (no allocation)."""
    if not (metrics.enabled() or tracing.enabled()):
        return tracing.NULL_SPAN
    return _StepScope()


def _exit_dumps():
    from .. import flags as _flags

    out = _flags.env("PTPU_METRICS_OUT")
    if out:
        try:
            metrics.dump_json(out)
        except OSError:
            pass
    tdir = _flags.env("PTPU_TRACE_DIR")
    if tdir:
        try:
            os.makedirs(tdir, exist_ok=True)
            tracing.dump_chrome_trace(os.path.join(tdir, "ptpu_trace.json"))
        except OSError:
            pass


from .. import flags as _flags  # noqa: E402  (stdlib-only, cycle-free)

if _flags.env("PTPU_METRICS_OUT") or _flags.env("PTPU_TRACE_DIR"):
    atexit.register(_exit_dumps)

if _flags.env("PTPU_METRICS_PORT") is not None:
    # live scrape surface, same conditional-startup pattern as the exit
    # dumps: no flag, no import, no thread
    from . import endpoint as _endpoint  # noqa: E402

    _endpoint.start()
