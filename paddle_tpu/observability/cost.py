"""Compiled-step cost accounting and MFU (model-FLOPs utilization).

On every compile-cache miss the executor's AOT path and the serving
model's step builders hand their freshly compiled executable here; XLA's
per-executable ``cost_analysis()``/``memory_analysis()`` (read through
the version-guarded ``core.jax_compat`` shims — absent APIs are a data
gap, not an error) become gauges:

  exec/step_flops           FLOPs of one compiled step
  exec/step_bytes_accessed  bytes read+written per step (memory traffic)
  exec/peak_hbm_bytes       argument+output+temp buffer footprint

``mfu_pct`` is the Chinchilla/PaLM-era utilization headline:
``step_flops * steps_per_sec / peak_flops``. The peak table is a
NOMINAL per-platform figure (one chip, dense bf16 for accelerators; a
token host figure for CPU so CI math stays finite and comparable run to
run) — MFU here is for tracking regressions against yourself, not for
cross-vendor marketing comparisons. bench.py publishes the
``bench/mfu_pct`` gauge and per-leg receipts from these numbers.
"""

from ..core import jax_compat as _jax_compat

__all__ = ["publish", "analyze", "peak_flops", "mfu_pct",
           "PLATFORM_PEAK_FLOPS"]

# nominal peak FLOPs per chip (dense bf16 class figures; CPU is a token
# reference point, not a measured host capability)
PLATFORM_PEAK_FLOPS = {
    "tpu": 275e12,
    "gpu": 312e12,
    "cpu": 1e11,
}


def peak_flops(platform=None):
    """The table entry for `platform` (default: the first jax device's
    platform; unknown platforms fall back to the CPU figure)."""
    if platform is None:
        try:
            import jax

            platform = jax.devices()[0].platform
        except Exception:
            platform = "cpu"
    return PLATFORM_PEAK_FLOPS.get(platform, PLATFORM_PEAK_FLOPS["cpu"])


def analyze(compiled):
    """{step_flops, step_bytes_accessed, peak_hbm_bytes} for one
    compiled executable — only the keys the backend actually reports."""
    out = {}
    ca = _jax_compat.compiled_cost_analysis(compiled)
    if ca:
        if "flops" in ca:
            out["step_flops"] = ca["flops"]
        if "bytes accessed" in ca:
            out["step_bytes_accessed"] = ca["bytes accessed"]
    ma = _jax_compat.compiled_memory_analysis(compiled)
    if ma:
        out["peak_hbm_bytes"] = (
            ma.get("argument_size_in_bytes", 0.0)
            + ma.get("output_size_in_bytes", 0.0)
            + ma.get("temp_size_in_bytes", 0.0))
    return out


def publish(compiled):
    """Publish the exec/* gauges for `compiled` into the process-wide
    registry (last compile wins — on a steady-state engine that is THE
    step) and return the analysis dict. Callers gate on
    metrics.enabled(); a backend reporting nothing publishes nothing."""
    vals = analyze(compiled)
    if not vals:
        return vals
    from . import metrics as _metrics

    reg = _metrics.registry()
    if "step_flops" in vals:
        reg.gauge("exec/step_flops").set(vals["step_flops"])
    if "step_bytes_accessed" in vals:
        reg.gauge("exec/step_bytes_accessed").set(
            vals["step_bytes_accessed"])
    if "peak_hbm_bytes" in vals:
        reg.gauge("exec/peak_hbm_bytes").set(vals["peak_hbm_bytes"])
    return vals


def mfu_pct(step_flops, steps_per_sec, platform=None):
    """Model-FLOPs utilization percent against the platform peak."""
    peak = peak_flops(platform)
    if not step_flops or not steps_per_sec or peak <= 0:
        return 0.0
    return 100.0 * float(step_flops) * float(steps_per_sec) / peak
