"""Crash-safe flight recorder: the fleet's black box.

A bounded ring of structured events fed by the failure-handling layers
(`resilience.py`, `serving/engine.py`, `serving/router.py`,
`data_plane.py`): step outcomes, replica health transitions
healthy→suspect→dead, rollbacks, fault-injector firings, anomaly
verdicts, KV-pool invariant results. On a fatal condition — uncaught
worker death, `LockCheckError`/invariant violation, SIGTERM drain,
`RetryBudgetExceededError` — the ring is dumped atomically (the
checkpoint tmp+rename pattern) into `PTPU_BLACKBOX_DIR`, so every
chaos-CI failure ships its own post-mortem artifact even when the
process dies before atexit telemetry runs.

Enablement contract (docs/OBSERVABILITY.md): OFF unless
`PTPU_BLACKBOX_DIR` is set (or `enable()` is called) — when off,
`record_event()` is a single bool check and the ring is never
allocated, so the defaults-off hot path is identical to a build without
this module. Event-type literals passed to `record_event()` are linted
against the docs (`event-undocumented`, tools/ptpu_lint.py) exactly
like metric names.

Locking: one leaf lock guards the ring (created through
`analysis.concurrency.make_lock` when the tracker is importable, so
`PTPU_LOCK_CHECK=1` orders it). Callers hold scheduler/router locks
while recording; the recorder itself takes nothing else, so every edge
points INTO this lock and no cycle is possible. `dump()` must stay
safe to call from exception handlers and the concurrency tracker's own
failure path — it touches only the ring lock and the filesystem.
"""

import atexit
import itertools
import json
import os
import sys
import threading
import time

__all__ = ["enabled", "enable", "disable", "record_event", "events",
           "dump", "dropped", "reset"]

_TMP_PREFIX = ".ptpu_tmp_"  # checkpoint.py's atomic-rename prefix

DEFAULT_CAPACITY = 4096


def _make_lock(name):
    """Tracked when the concurrency tracker is loaded; STRICTLY passive
    about the import (metrics.py's bootstrap rationale applies: this
    module is importable before `paddle_tpu.analysis` exists)."""
    conc = sys.modules.get("paddle_tpu.analysis.concurrency")
    if conc is None:
        return threading.Lock()
    return conc.make_lock(name)


_ENABLED = False
_DIR = None
_events = None  # deque, allocated on first enable
_dropped = 0
_lock = threading.Lock()  # replaced by a tracked lock on enable
_dump_seq = itertools.count(1)


def enabled():
    return _ENABLED


def enable(directory=None, capacity=None):
    """Turn the recorder on (programmatic alternative to
    PTPU_BLACKBOX_DIR). `directory` is where dumps land; None keeps the
    previous/flag-derived one (events still ring-buffer without a
    directory, dump() just returns None)."""
    import collections

    global _ENABLED, _DIR, _events, _lock
    if capacity is None:
        capacity = _events.maxlen if _events is not None else \
            DEFAULT_CAPACITY
    if directory is not None:
        _DIR = directory
    if _events is None or _events.maxlen != capacity:
        _events = collections.deque(maxlen=capacity)
        _lock = _make_lock("obs.blackbox")
    _ENABLED = True


def disable():
    global _ENABLED
    _ENABLED = False


def record_event(etype, **fields):
    """Append one structured event to the ring; a single bool check when
    the recorder is off. `etype` is a documented literal (see the
    flight-recorder schema table in docs/OBSERVABILITY.md)."""
    if not _ENABLED:
        return
    ev = dict(fields)
    ev["ts"] = time.time()
    ev["type"] = etype
    ev["thread"] = threading.current_thread().name
    global _dropped
    with _lock:
        if len(_events) == _events.maxlen:
            _dropped += 1  # deque evicts the oldest on append
        _events.append(ev)


def events():
    """Snapshot of the ring (oldest first)."""
    if _events is None:
        return []
    with _lock:
        return list(_events)


def dropped():
    return _dropped


def reset():
    global _dropped
    if _events is not None:
        with _lock:
            _events.clear()
            _dropped = 0


def dump(reason):
    """Atomically write the ring to PTPU_BLACKBOX_DIR as
    ptpu_blackbox_<pid>_<seq>_<reason>.json (tmp file + os.rename, the
    PR-4 checkpoint pattern — a crash mid-write leaves only a .ptpu_tmp_
    file, never a torn dump). Returns the path, or None when disabled /
    no directory / the write fails (dump runs on failure paths and must
    never mask the original error)."""
    if not _ENABLED or not _DIR:
        return None
    with _lock:
        evs = list(_events)
        n_dropped = _dropped
    doc = {"reason": reason, "pid": os.getpid(), "time": time.time(),
           "dropped_events": n_dropped, "events": evs}
    name = "ptpu_blackbox_%d_%03d_%s.json" % (
        os.getpid(), next(_dump_seq), reason)
    tmp = os.path.join(_DIR, _TMP_PREFIX + name)
    try:
        os.makedirs(_DIR, exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2)
            f.flush()
            os.fsync(f.fileno())
        final = os.path.join(_DIR, name)
        os.rename(tmp, final)
    except (OSError, TypeError, ValueError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    return final


def _flag_init():
    from .. import flags as _flags

    bdir = _flags.env("PTPU_BLACKBOX_DIR")
    if bdir:
        cap = _flags.env("PTPU_BLACKBOX_EVENTS")
        enable(str(bdir), int(cap) if cap else None)
        # a final dump at clean exit so the artifact exists even when no
        # fatal trigger fired (the fleet CI leg reads this one: it holds
        # both the replica_dead and the later readmit events)
        atexit.register(dump, "exit")


_flag_init()
