"""Span-based host tracing with Chrome-trace/Perfetto JSON export.

`span("compile")` is a nestable, thread-safe context manager. Each
completed span is recorded as one chrome://tracing complete ("X") event
(the format tools/timeline.py merges and Perfetto/chrome://tracing open
directly). Device-side alignment: while a `jax.profiler` trace is
active, every span also enters a `jax.profiler.TraceAnnotation`, so the
host spans show up on the XPlane timeline next to the XLA device rows —
the CUPTI DeviceTracer correlation the reference had (SURVEY §5.1).
Spans are additionally forwarded to the native C++ collector
(native/profiler.cc ptpu_prof_mark) when it is loaded and enabled, so
one chrome-trace dump can carry Python, C++, and device work.

Enablement mirrors metrics.py: OFF unless `PTPU_TRACE=1` or
`PTPU_TRACE_DIR=<dir>` is set (or `enable()` is called); when off,
`span()` returns a shared null context manager — no per-call
allocation. Buffering is a bounded ring (`MAX_EVENTS`): the newest
spans win, and the dump carries a `ptpuDroppedSpans` eviction count.
"""

import collections
import itertools
import json
import os
import threading
import time

__all__ = ["span", "complete", "instant", "new_trace_id", "enabled",
           "enable", "disable", "events", "dump_chrome_trace", "reset",
           "MAX_EVENTS"]

MAX_EVENTS = 200000

# ring buffer: the NEWEST spans win (the tail of a long run is what gets
# debugged); evictions are counted into the dump's ptpuDroppedSpans note
_events = collections.deque(maxlen=MAX_EVENTS)
_dropped = 0
# deliberately a PLAIN lock, not a tracked one (docs/STATIC_ANALYSIS.md):
# this module executes during package bootstrap, before
# paddle_tpu.analysis exists, and the ring-buffer append it guards is the
# tracing hot path — it nests no other lock, so there is no order to
# observe
_lock = threading.Lock()
_pid = os.getpid()

# request-scoped tracing identity: trace ids are minted once per request
# (ServingEngine.submit / RouterRequest) and survive failover re-dispatch;
# span ids are minted per recorded span. itertools.count is a single
# C-level op, safe to share across threads without the ring lock.
_trace_seq = itertools.count(1)
_span_seq = itertools.count(1)


def new_trace_id():
    """Process-unique request trace id ("<pid>.<seq>" hex)."""
    return "%x.%x" % (_pid, next(_trace_seq))

_jax_profiler = None  # resolved lazily; False = unavailable


def _annotation(name):
    """jax.profiler.TraceAnnotation if jax is importable, else None."""
    global _jax_profiler
    if _jax_profiler is None:
        try:
            from jax import profiler as jp
            _jax_profiler = jp
        except Exception:
            _jax_profiler = False
    if _jax_profiler:
        try:
            return _jax_profiler.TraceAnnotation(name)
        except Exception:
            return None
    return None


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **args):
        return self  # chains like Span.set: `with span(...).set(...)`


NULL_SPAN = _NullSpan()


class Span:
    __slots__ = ("name", "args", "trace_id", "span_id", "parent_id",
                 "_t0", "_ann")

    def __init__(self, name, args=None, trace_id=None, parent_id=None):
        self.name = name
        self.args = args
        self.trace_id = trace_id
        self.parent_id = parent_id
        # span ids only exist on request-scoped spans: anonymous spans
        # keep the exact pre-trace_id event shape (defaults-off identity)
        self.span_id = next(_span_seq) if trace_id is not None else None
        self._t0 = 0
        self._ann = None

    def set(self, **args):
        """Attach key/values rendered in the trace viewer's args pane."""
        if self.args is None:
            self.args = args
        else:
            self.args.update(args)
        return self

    def __enter__(self):
        ann = _annotation(self.name)
        if ann is not None:
            ann.__enter__()
        self._ann = ann
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter_ns()
        if self._ann is not None:
            self._ann.__exit__(*exc)
        ts = self._t0 // 1000
        dur = (t1 - self._t0) // 1000
        ev = {"name": self.name, "ph": "X", "pid": _pid,
              "tid": threading.get_ident() % 100000, "ts": ts, "dur": dur,
              "cat": "host"}
        args = self.args
        if self.trace_id is not None:
            args = dict(args) if args else {}
            args["trace_id"] = self.trace_id
            args["span_id"] = self.span_id
            if self.parent_id is not None:
                args["parent_id"] = self.parent_id
        if args:
            ev["args"] = args
        _record(ev)
        _forward_native(self.name, ts, ts + dur)
        return False


def _record(ev):
    global _dropped
    evicted = False
    with _lock:
        if len(_events) == MAX_EVENTS:
            _dropped += 1  # deque evicts the oldest on append
            evicted = True
        _events.append(ev)
    if evicted:
        # promoted to a first-class counter so CI can gate on trace loss
        # without parsing the chrome dump; incremented OUTSIDE the plain
        # ring lock — the metric's tracked lock must not nest under it
        _metrics.counter("trace/dropped_spans").inc()


def _forward_native(name, us_start, us_end):
    """Mirror the span into the C++ collector when it is live+enabled,
    so ptpu_prof_dump_chrome sees host spans too."""
    try:
        from ..core import native

        l = native.lib()
        if l is not None and l.ptpu_prof_enabled():
            l.ptpu_prof_mark(name.encode(), us_start, us_end)
    except Exception:
        pass


from . import metrics as _metrics
from .metrics import _env_on  # central flags-registry check

_ENABLED = _env_on("PTPU_TRACE") or _env_on("PTPU_TRACE_DIR")


def enabled():
    return _ENABLED


def enable():
    global _ENABLED
    _ENABLED = True


def disable():
    global _ENABLED
    _ENABLED = False


def span(name, trace_id=None, parent_id=None, **args):
    """A context manager timing one named region; nested spans nest in
    the exported trace. No-op singleton (zero allocation) when disabled.
    Pass `trace_id` (from `new_trace_id()`) to stamp the span with a
    request identity — it gets a span id, and `trace_id`/`span_id`/
    `parent_id` land in the event's args pane."""
    if not _ENABLED:
        return NULL_SPAN
    return Span(name, args or None, trace_id, parent_id)


def complete(name, t0_ns, t1_ns, trace_id=None, parent_id=None, **args):
    """Record an already-timed region as one complete event with explicit
    `perf_counter_ns` bounds — for retroactive request-scoped spans such
    as queue_wait, whose start predates the emit site. Returns the span
    id (None when tracing is off or no trace_id was given)."""
    if not _ENABLED:
        return None
    span_id = next(_span_seq) if trace_id is not None else None
    ts = t0_ns // 1000
    dur = max(0, (t1_ns - t0_ns) // 1000)
    ev = {"name": name, "ph": "X", "pid": _pid,
          "tid": threading.get_ident() % 100000, "ts": ts, "dur": dur,
          "cat": "host"}
    if trace_id is not None:
        args["trace_id"] = trace_id
        args["span_id"] = span_id
        if parent_id is not None:
            args["parent_id"] = parent_id
    if args:
        ev["args"] = args
    _record(ev)
    _forward_native(name, ts, ts + dur)
    return span_id


def instant(name, trace_id=None, parent_id=None, **args):
    """Zero-duration marker event at now (readmit, deadline_expired)."""
    t = time.perf_counter_ns()
    return complete(name, t, t, trace_id=trace_id, parent_id=parent_id,
                    **args)


def events():
    """Snapshot of the recorded chrome-trace events."""
    with _lock:
        return list(_events)


def reset():
    global _dropped
    with _lock:
        _events.clear()
        _dropped = 0


def dump_chrome_trace(path):
    """Write {"traceEvents": [...]} Chrome-trace JSON (open in Perfetto:
    ui.perfetto.dev > Open trace file). Returns the event count."""
    with _lock:
        evs = list(_events)
        dropped = _dropped
    doc = {"traceEvents": evs, "displayTimeUnit": "ms"}
    if dropped:
        doc["ptpuDroppedSpans"] = dropped
    with open(path, "w") as f:
        json.dump(doc, f)
    return len(evs)
