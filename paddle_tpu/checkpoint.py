"""Sharded training-state checkpointing (SURVEY §5.4 design mapping:
"orbax-style checkpoint of a param pytree + serialization versioning";
reference counterpart: save/load_persistables io.py:460 + the distributed
snapshot flow §5.3).

Unlike the Fluid-parity io.py (whole-array save of scope persistables),
this module checkpoints an arbitrary jax pytree — including
NamedSharding'd arrays from an SPMD mesh — via orbax, so every host writes
only its shards and restore re-shards onto the current mesh. Works for
single-chip state too.

Crash safety (docs/RESILIENCE.md): every save is ATOMIC — the orbax tree
and a `ptpu_manifest.json` of per-leaf content digests are written into a
hidden temp dir, fsynced, and `os.rename`d into place, so a crash mid-save
can never leave a `step_N` that `latest_checkpoint` would hand back.
Restore verifies the digests and — when pointed at a directory — falls
back to the newest INTACT step, counting what it skipped in
`resilience/ckpt_corrupt_detected`. `CheckpointManager(async_save=True)`
writes on a background thread from a host copy taken synchronously, so
donated device buffers can't be torn by the next step.

Layout (one step):
    directory/step_N/ptpu_manifest.json   digests + leaf inventory
    directory/step_N/data/...             the orbax pytree checkpoint
Legacy step dirs (orbax files directly under step_N, no manifest) still
restore when named explicitly, but are treated as torn by directory-level
scans — a manifest is the completeness marker.
"""

import hashlib
import json
import os
import shutil
import threading

import numpy as np

from .observability import metrics as _metrics
from .observability import tracing as _tracing

__all__ = ["save_checkpoint", "restore_checkpoint", "latest_checkpoint",
           "all_checkpoints", "checkpoints_after", "CheckpointManager",
           "CheckpointCorruptionError", "MANIFEST_NAME"]

MANIFEST_NAME = "ptpu_manifest.json"
_DATA_SUBDIR = "data"
_TMP_PREFIX = ".ptpu_tmp_"


class CheckpointCorruptionError(RuntimeError):
    """A checkpoint failed digest verification (torn write, bit rot) or
    its payload cannot be deserialized."""


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def _norm_path(path):
    """Keypath -> stable tuple of strings (sequence indices and dict/attr
    keys normalized), shared by digest manifests and target placement so
    orbax's loose container round-trip (tuples come back as lists) cannot
    desynchronize them."""
    out = []
    for k in path:
        if hasattr(k, "idx"):
            out.append(str(k.idx))
        elif hasattr(k, "key"):
            out.append(str(k.key))
        elif hasattr(k, "name"):
            out.append(str(k.name))
        else:
            out.append(str(k))
    return tuple(out)


def _leaf_digest(leaf):
    """sha256 over a leaf's host bytes + dtype + shape, or None when the
    leaf is not fully addressable from this host (multi-host shards: the
    local view would hash differently per process)."""
    if leaf is None:
        return None
    addressable = getattr(leaf, "is_fully_addressable", True)
    if not addressable:
        return None
    try:
        arr = np.asarray(leaf)
    except (TypeError, ValueError):
        return None
    h = hashlib.sha256()
    h.update(str(arr.dtype).encode())
    h.update(repr(arr.shape).encode())
    h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def _flatten_with_keys(tree):
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [("/".join(_norm_path(p)), leaf) for p, leaf in flat]


def _write_manifest(path, state, step):
    digests = {key: _leaf_digest(leaf)
               for key, leaf in _flatten_with_keys(state)}
    # file inventory (relpath -> size): lets directory scans detect a
    # truncation-torn payload with a handful of stat calls — full digest
    # verification stays a restore-time concern
    files = {}
    for root, _dirs, names in os.walk(path):
        for name in names:
            if name == MANIFEST_NAME:
                continue
            p = os.path.join(root, name)
            files[os.path.relpath(p, path)] = os.path.getsize(p)
    doc = {"format": 1, "step": int(step), "digests": digests,
           "files": files}
    mpath = os.path.join(path, MANIFEST_NAME)
    with open(mpath, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    return doc


def _read_manifest(path):
    mpath = os.path.join(path, MANIFEST_NAME)
    if not os.path.isfile(mpath):
        return None
    try:
        with open(mpath) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _files_intact(path, manifest):
    """Cheap (stat-only) truncation check of the manifest's file
    inventory. Manifests without one (older format) pass — digest
    verification at restore still covers them."""
    files = manifest.get("files")
    if not files:
        return True
    for rel, size in files.items():
        p = os.path.join(path, rel)
        try:
            if os.path.getsize(p) != int(size):
                return False
        except OSError:
            return False
    return True


def _orbax_path(step_path):
    data = os.path.join(step_path, _DATA_SUBDIR)
    # legacy (pre-manifest) checkpoints hold the orbax tree directly
    return data if os.path.isdir(data) else step_path


def _fsync_dir(path):
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass  # fsync on a dir is best-effort (not all filesystems)


def _maybe_tear(step_path):
    """`ckpt_torn_write` fault injection: after a save lands, corrupt its
    largest payload file in place — the torn write the digest manifest
    exists to catch. Routed through the global injector so the hook costs
    one predicate when injection is off."""
    from .resilience import global_injector

    if not global_injector().fire_occurrence("ckpt_torn_write"):
        return
    for root, _dirs, files in os.walk(step_path):
        for name in files:
            if name == MANIFEST_NAME:
                continue
            p = os.path.join(root, name)
            with open(p, "r+b") as f:
                data = f.read()
                if not data:
                    continue
                f.seek(0)
                f.write(bytes(b ^ 0xFF
                              for b in data[: max(1, len(data) // 2)]))
                f.truncate(max(1, len(data) // 2))


def _dist_info():
    """(process_index, process_count) — (0, 1) when jax is absent or
    uninitialized."""
    try:
        import jax

        return jax.process_index(), jax.process_count()
    except Exception:
        return 0, 1


def save_checkpoint(directory, state, step):
    """Atomically write `state` (any jax pytree, sharded arrays included)
    under directory/step_N: orbax tree + digest manifest land in a temp
    dir first, then one rename publishes the step. Under jax.distributed
    (process_count > 1) every process must participate in ONE coordinated
    orbax save, so per-host tmp+rename cannot work; there the tree is
    written in place and the manifest — written LAST, by process 0 — is
    the publish/completeness marker instead. Returns the checkpoint
    path."""
    directory = os.path.abspath(directory)
    final = os.path.join(directory, "step_%d" % int(step))
    pidx, pcount = _dist_info()
    os.makedirs(directory, exist_ok=True)
    with _tracing.span("checkpoint/save", step=int(step)):
        if pcount > 1:
            # drop any stale manifest first: while the payload is being
            # rewritten the step must read as incomplete
            mpath = os.path.join(final, MANIFEST_NAME)
            if pidx == 0 and os.path.isfile(mpath):
                os.remove(mpath)
            _checkpointer().save(os.path.join(final, _DATA_SUBDIR),
                                 state, force=True)
            if pidx == 0:
                _write_manifest(final, state, step)
                _fsync_dir(directory)
        else:
            tmp = os.path.join(directory,
                               _TMP_PREFIX + "step_%d" % int(step))
            shutil.rmtree(tmp, ignore_errors=True)
            _checkpointer().save(os.path.join(tmp, _DATA_SUBDIR), state,
                                 force=True)
            _write_manifest(tmp, state, step)
            aside = None
            if os.path.isdir(final):
                # overwriting the same step must stay atomic: park the
                # old dir aside first — rmtree-then-rename would leave
                # NO intact step_N if the process dies in between. A
                # crash between the two renames is healed by
                # _reap_stale_tmp's journal replay.
                aside = tmp + "_old"
                shutil.rmtree(aside, ignore_errors=True)
                os.rename(final, aside)
            os.rename(tmp, final)
            _fsync_dir(directory)
            if aside is not None:
                shutil.rmtree(aside, ignore_errors=True)
    _metrics.counter("resilience/ckpt_saves").inc()
    if pidx == 0:
        _maybe_tear(final)
    return final


def _scan_steps(directory, level="intact"):
    """[(step, path)] newest first, filtered by `level`:
      "all"      every step_N dir
      "manifest" steps with a manifest (the completeness marker a crash
                 mid-save never writes) — restore-candidate set: a
                 size-torn step is TRIED so its corruption is counted
      "intact"   manifest present AND file inventory passes the stat
                 check — what latest_checkpoint hands back and what GC
                 retention counts"""
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return []
    out = []
    for name in os.listdir(directory):
        if not name.startswith("step_"):
            continue
        try:
            step = int(name.split("_", 1)[1])
        except ValueError:
            continue
        path = os.path.join(directory, name)
        if level != "all":
            manifest = _read_manifest(path)
            if manifest is None:
                continue
            if level == "intact" and not _files_intact(path, manifest):
                continue
        out.append((step, path))
    out.sort(reverse=True)
    return out


def all_checkpoints(directory):
    """Intact (manifest present, file inventory passing) step numbers
    under directory, ascending."""
    return sorted(step for step, _ in _scan_steps(directory))


def checkpoints_after(directory, step):
    """Intact step numbers strictly newer than ``step`` (None = all),
    ascending — the OnlineUpdater's poll primitive: a live trainer's
    async saves become visible here only once their manifest landed, so
    each step is an export candidate exactly once and a save still in
    flight is never exported torn."""
    steps = all_checkpoints(directory)
    if step is None:
        return steps
    step = int(step)
    return [s for s in steps if s > step]


def latest_checkpoint(directory):
    """Most recent INTACT step_N path under directory, or None. Steps
    without a digest manifest (a crash mid-save, a foreign writer) are
    skipped — handing back a torn directory is how a dead run stays
    dead."""
    steps = _scan_steps(directory)
    return steps[0][1] if steps else None


def _verify_digests(path, raw):
    """Compare the restored tree's per-leaf digests against the manifest;
    raises CheckpointCorruptionError naming the first mismatch. Legacy
    checkpoints (no manifest) pass through unverified."""
    manifest = _read_manifest(path)
    if manifest is None:
        return
    want = manifest.get("digests", {})
    got = dict(_flatten_with_keys(raw))
    if set(want) != set(got):
        raise CheckpointCorruptionError(
            "checkpoint %s leaf inventory mismatch: manifest has %d "
            "leaves, payload has %d" % (path, len(want), len(got)))
    for key, digest in want.items():
        if digest is None:
            continue  # leaf was not addressable at save time
        actual = _leaf_digest(got[key])
        if actual != digest:
            raise CheckpointCorruptionError(
                "checkpoint %s leaf %r failed digest verification "
                "(torn write or corruption)" % (path, key))


def _restore_step(path, verify=True):
    if verify:
        manifest = _read_manifest(path)
        if manifest is not None and not _files_intact(path, manifest):
            raise CheckpointCorruptionError(
                "checkpoint %s payload files do not match the manifest "
                "inventory (truncated/torn write)" % path)
    try:
        raw = _checkpointer().restore(_orbax_path(path))
    except CheckpointCorruptionError:
        raise
    except Exception as exc:  # orbax deserialization of a torn payload
        raise CheckpointCorruptionError(
            "checkpoint %s failed to deserialize: %s" % (path, exc))
    if verify:
        _verify_digests(path, raw)
    return raw


def _place_like(raw, target_state):
    """Place restored leaves onto `target_state`'s structure/shardings —
    keypath-matched (see _norm_path) so renamed/reordered same-shape
    weights fail loudly instead of restoring into the wrong slots."""
    import jax

    raw_paths = jax.tree_util.tree_flatten_with_path(raw)[0]
    t_paths, treedef = jax.tree_util.tree_flatten_with_path(target_state)
    if len(raw_paths) != len(t_paths):
        raise ValueError(
            "checkpoint has %d leaves but target_state has %d"
            % (len(raw_paths), len(t_paths)))
    raw_by_key = {_norm_path(p): leaf for p, leaf in raw_paths}
    raw_leaves, t_leaves = [], []
    for p, t in t_paths:
        key = _norm_path(p)
        if key not in raw_by_key:
            raise ValueError(
                "target_state leaf %r not found in checkpoint (checkpoint "
                "keys: %s...)" % ("/".join(key), sorted(raw_by_key)[:8]))
        raw_leaves.append(raw_by_key[key])
        t_leaves.append(t)
    placed = []
    for r, t in zip(raw_leaves, t_leaves):
        arr = np.asarray(r)
        if hasattr(t, "shape") and tuple(t.shape) != arr.shape:
            raise ValueError("leaf shape mismatch: checkpoint %s vs target "
                             "%s" % (arr.shape, tuple(t.shape)))
        sharding = getattr(t, "sharding", None)
        if isinstance(sharding, jax.sharding.NamedSharding):
            placed.append(jax.device_put(arr, sharding))
        else:
            # leave non-mesh leaves UNcommitted (a committed single-device
            # scalar could not be mixed with mesh-sharded args under jit)
            placed.append(jax.numpy.asarray(arr, dtype=getattr(
                t, "dtype", None)))
    return jax.tree.unflatten(treedef, placed)


def restore_checkpoint(directory_or_path, target_state=None, verify=True):
    """Restore a pytree checkpoint with digest verification. With
    `target_state` (an abstract or concrete pytree of the expected
    structure/shardings — e.g. the fresh `trainer.init()` output) the
    restored arrays are placed to match it; without, the stored structure
    is returned as saved.

    `directory_or_path` may be a step path (one attempt; corruption
    raises CheckpointCorruptionError) or the checkpoint dir — there,
    steps are tried newest-intact first and corrupt ones are skipped with
    a warning + `resilience/ckpt_corrupt_detected`, so one torn write
    costs one checkpoint interval, not the run."""
    path = directory_or_path
    if os.path.basename(path).startswith("step_"):
        raw = _restore_step(path, verify=verify)
        return raw if target_state is None else _place_like(raw,
                                                            target_state)
    # candidate set is manifest-bearing steps (not just size-intact
    # ones): a size-torn step must be TRIED and FAIL so its corruption
    # is warned about and counted, not silently ignored. Manifest-less
    # dirs (the pre-manifest writer's format — the atomic tmp+rename
    # writer never publishes a step without one) are last-resort
    # candidates, so upgrading an existing run still resumes.
    manifested = _scan_steps(path, level="manifest")
    seen = {p for _s, p in manifested}
    legacy = [(s, p) for s, p in _scan_steps(path, level="all")
              if p not in seen]
    if not manifested and not legacy:
        raise FileNotFoundError("no step_N checkpoints under %r" % path)
    last_exc = None
    for is_legacy, step, step_path in (
            [(False, s, p) for s, p in manifested]
            + [(True, s, p) for s, p in legacy]):
        try:
            with _tracing.span("checkpoint/restore", step=step):
                raw = _restore_step(step_path, verify=verify)
        except CheckpointCorruptionError as exc:
            last_exc = exc
            _metrics.counter("resilience/ckpt_corrupt_detected").inc()
            import warnings

            warnings.warn(
                "skipping corrupt checkpoint %s: %s" % (step_path, exc),
                RuntimeWarning)
            continue
        if is_legacy:
            import warnings

            warnings.warn(
                "restored pre-manifest checkpoint %s (no digest "
                "verification possible)" % step_path, RuntimeWarning)
        return (raw if target_state is None
                else _place_like(raw, target_state))
    raise CheckpointCorruptionError(
        "every checkpoint under %r is corrupt (last: %s)"
        % (path, last_exc))


class CheckpointManager:
    """Rolling checkpoint manager (keep the newest `max_to_keep`) — the
    coordinated-snapshot shape of §5.3's checkpoint_notify flow, minus the
    pserver RPC: under jax.distributed every process participates in the
    same orbax save.

    `async_save=True` moves the filesystem write to a background thread:
    `save` first copies every leaf to host memory IN THE CALLER (that is
    the consistency point — the next step may donate the very buffers
    being saved), then returns while the orbax write + manifest + rename
    run behind. At most one save is in flight; `wait()` (or the next
    `save`) joins it and re-raises any background failure."""

    def __init__(self, directory, max_to_keep=3, async_save=False):
        from .analysis.concurrency import make_lock

        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        self.async_save = bool(async_save)
        # named lock sites (docs/STATIC_ANALYSIS.md): `_mu` guards the
        # background-save handoff fields (`_thread`, `_error`) — without
        # it, concurrent wait() callers race the join/clear sequence and
        # a background failure can be dropped. `_save_mu` serializes
        # whole save() calls (the at-most-one-in-flight contract): two
        # concurrent save()s would otherwise both pass the leading
        # wait() and the second spawn would drop the first writer's
        # handle, letting wait() return mid-write. The writer thread
        # takes only `_mu`, so holding `_save_mu` across its join cannot
        # deadlock.
        self._mu = make_lock("checkpoint.manager")
        self._save_mu = make_lock("checkpoint.manager.save")
        self._thread = None
        self._error = None
        os.makedirs(self.directory, exist_ok=True)
        self._reap_stale_tmp()

    def _reap_stale_tmp(self):
        """Journal replay for a writer that died mid-publish. A complete
        tmp dir (manifest present) whose step_N is missing finishes its
        crashed rename; an `_old` aside whose step_N is missing is the
        pre-overwrite original and is restored; everything else from a
        crashed writer is dead weight and reclaimed. Only process 0 may
        touch shared temp state under jax.distributed."""
        if _dist_info()[0] != 0:
            return
        asides = []
        for name in sorted(os.listdir(self.directory)):
            if not name.startswith(_TMP_PREFIX):
                continue
            path = os.path.join(self.directory, name)
            target = name[len(_TMP_PREFIX):]
            if target.endswith("_old"):
                asides.append((path, target[:-len("_old")]))
                continue
            final = os.path.join(self.directory, target)
            if (target.startswith("step_")
                    and not os.path.isdir(final)
                    and _read_manifest(path) is not None):
                os.rename(path, final)  # finish the crashed publish
            else:
                shutil.rmtree(path, ignore_errors=True)
        for path, target in asides:
            final = os.path.join(self.directory, target)
            if target.startswith("step_") and not os.path.isdir(final):
                os.rename(path, final)  # restore the parked original
            else:
                shutil.rmtree(path, ignore_errors=True)

    def _host_state(self, state):
        """(host_copy, all_addressable). Multi-host shards cannot be
        copied to one host — the caller must fall back to a blocking
        save rather than let the background write race donation."""
        import jax

        holdouts = []

        def copy_leaf(leaf):
            addressable = getattr(leaf, "is_fully_addressable", True)
            if not addressable:
                holdouts.append(leaf)
                return leaf
            if hasattr(leaf, "dtype"):
                return np.array(leaf)  # forced copy off device buffers
            return leaf

        copied = jax.tree.map(copy_leaf, state)
        return copied, not holdouts

    def save(self, state, step, blocking=None, host_copied=False):
        """Write one checkpoint and GC old steps. Returns the final path
        (async saves return it even though the write is still landing —
        `wait()` before depending on it). `host_copied=True` promises
        `state` is already a private host copy (e.g. a resilience
        ScopeSnapshot), skipping the defensive per-leaf copy.

        Serialized: concurrent save() callers queue behind `_save_mu`,
        so the join-the-previous-writer-then-spawn sequence is atomic
        and at most one write is ever in flight."""
        with self._save_mu:
            return self._save_locked(state, step, blocking, host_copied)

    def _save_locked(self, state, step, blocking, host_copied):
        self.wait()
        if blocking is None:
            blocking = not self.async_save
        final = os.path.join(self.directory, "step_%d" % int(step))
        if not blocking and not host_copied:
            state, all_addressable = self._host_state(state)
            if not all_addressable:
                # non-addressable shards stayed live device arrays; a
                # background write would race the next step's donation
                import warnings

                warnings.warn(
                    "checkpoint state holds non-fully-addressable "
                    "shards; saving step %d synchronously" % int(step),
                    RuntimeWarning)
                blocking = True
        if blocking:
            save_checkpoint(self.directory, state, step)
            self._gc()
            return final
        host_state = state

        def _write():
            try:
                save_checkpoint(self.directory, host_state, step)
                self._gc()
            except BaseException as exc:  # surfaced by wait()
                with self._mu:
                    self._error = exc

        t = threading.Thread(target=_write, name="ptpu-ckpt-save",
                             daemon=True)
        # start BEFORE publishing: a concurrent wait() that reads the
        # handle must never join an unstarted thread (RuntimeError). A
        # wait() landing in the gap just misses the writer — the same
        # outcome as calling wait() a moment earlier — and the next
        # save() is serialized behind _save_mu, which we still hold
        t.start()
        with self._mu:
            self._thread = t
        return final

    def wait(self):
        """Join the in-flight async save (if any); re-raises a background
        write failure here, in the caller's thread. Thread-safe: the
        join runs OUTSIDE the handoff lock (the writer only needs it for
        the error latch, so a join under the lock could not deadlock,
        but holding a lock across a join is exactly what the
        blocking-while-holding rule exists to flag)."""
        with self._mu:
            t = self._thread
        if t is not None:
            t.join()
            with self._mu:
                if self._thread is t:
                    self._thread = None
        with self._mu:
            exc, self._error = self._error, None
        if exc is not None:
            raise exc

    def restore(self, target_state=None):
        """Newest-intact-first restore with corruption fallback (see
        restore_checkpoint)."""
        self.wait()
        return restore_checkpoint(self.directory, target_state)

    def latest_step(self):
        steps = self.all_steps()
        return steps[-1] if steps else None

    def all_steps(self):
        return all_checkpoints(self.directory)

    def _gc(self):
        if not self.max_to_keep:
            return
        # retention is counted over INTACT steps only — a torn step must
        # never push an intact fallback out of the quota (with
        # max_to_keep=1, intact N then torn M would otherwise delete N
        # and leave the run unrecoverable). Non-intact dirs (fault-torn,
        # or the pre-manifest writer's legacy format, both still restore
        # fallbacks) are reclaimed only once a full quota of NEWER
        # intact steps exists to fall back to instead.
        intact = _scan_steps(self.directory)  # newest first
        keep = {path for _s, path in intact[:self.max_to_keep]}
        intact_paths = {path for _s, path in intact}
        intact_steps = [s for s, _p in intact]
        for step, path in _scan_steps(self.directory, level="all"):
            if path in keep:
                continue
            if path not in intact_paths:
                newer_intact = sum(1 for s in intact_steps if s > step)
                if newer_intact < self.max_to_keep:
                    continue
            shutil.rmtree(path, ignore_errors=True)


def host_embedding_state():
    """The sparse half of a recommender checkpoint: every registered
    host embedding table's shards + optimizer accumulators
    (docs/RECOMMENDER.md), as one nested numpy tree that rides the
    manifest unchanged. Flush any running Communicator first so queued
    pushes are in the snapshot."""
    from .parallel.host_embedding import tables_state_dict

    return tables_state_dict()


def load_host_embedding_state(state):
    """Restore host_embedding_state() output into the live table
    registry — tables must already exist (model build creates them) and
    match geometry, else EmbeddingStateError names the mismatch."""
    from .parallel.host_embedding import load_tables_state_dict

    load_tables_state_dict(state)
