"""Sharded training-state checkpointing (SURVEY §5.4 design mapping:
"orbax-style checkpoint of a param pytree + serialization versioning";
reference counterpart: save/load_persistables io.py:460 + the distributed
snapshot flow §5.3).

Unlike the Fluid-parity io.py (whole-array save of scope persistables),
this module checkpoints an arbitrary jax pytree — including
NamedSharding'd arrays from an SPMD mesh — via orbax, so every host writes
only its shards and restore re-shards onto the current mesh. Works for
single-chip state too.
"""

import os

__all__ = ["save_checkpoint", "restore_checkpoint", "latest_checkpoint",
           "CheckpointManager"]


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save_checkpoint(directory, state, step):
    """Write `state` (any jax pytree, sharded arrays included) under
    directory/step_N. Returns the checkpoint path."""
    path = os.path.join(os.path.abspath(directory), "step_%d" % int(step))
    _checkpointer().save(path, state, force=True)
    return path


def latest_checkpoint(directory):
    """Most recent step_N path under directory, or None."""
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return None
    steps = []
    for name in os.listdir(directory):
        if name.startswith("step_"):
            try:
                steps.append(int(name.split("_", 1)[1]))
            except ValueError:
                continue
    if not steps:
        return None
    return os.path.join(directory, "step_%d" % max(steps))


def restore_checkpoint(directory_or_path, target_state=None):
    """Restore a pytree checkpoint. With `target_state` (an abstract or
    concrete pytree of the expected structure/shardings — e.g. the fresh
    `trainer.init()` output) the restored arrays are placed to match it;
    without, the stored structure is returned as saved. `directory_or_path`
    may be the checkpoint dir (latest step is used) or a step path."""
    path = directory_or_path
    if not os.path.basename(path).startswith("step_"):
        latest = latest_checkpoint(path)
        if latest is None:
            raise FileNotFoundError("no step_N checkpoints under %r" % path)
        path = latest
    ckpt = _checkpointer()
    raw = ckpt.restore(path)
    if target_state is None:
        return raw
    import jax
    import numpy as np

    # orbax round-trips containers loosely (tuples come back as lists), so
    # match by keypath — with sequence indices and dict/attr keys
    # normalized to plain strings, stable across that transformation — and
    # place each leaf onto the target's sharding (device_put with a
    # NamedSharding re-shards onto the current mesh). Shape alone is not
    # enough: many transformer weights share a shape, and a silent
    # order-based match would restore renamed/reordered keys into the
    # wrong slots.
    raw_paths = jax.tree_util.tree_flatten_with_path(raw)[0]
    t_paths, treedef = jax.tree_util.tree_flatten_with_path(target_state)
    if len(raw_paths) != len(t_paths):
        raise ValueError(
            "checkpoint has %d leaves but target_state has %d"
            % (len(raw_paths), len(t_paths)))

    def _norm(path):
        out = []
        for k in path:
            if hasattr(k, "idx"):
                out.append(str(k.idx))
            elif hasattr(k, "key"):
                out.append(str(k.key))
            elif hasattr(k, "name"):
                out.append(str(k.name))
            else:
                out.append(str(k))
        return tuple(out)

    raw_by_key = {_norm(p): leaf for p, leaf in raw_paths}
    raw_leaves, t_leaves = [], []
    for p, t in t_paths:
        key = _norm(p)
        if key not in raw_by_key:
            raise ValueError(
                "target_state leaf %r not found in checkpoint (checkpoint "
                "keys: %s...)" % ("/".join(key),
                                  sorted(raw_by_key)[:8]))
        raw_leaves.append(raw_by_key[key])
        t_leaves.append(t)
    placed = []
    for r, t in zip(raw_leaves, t_leaves):
        arr = np.asarray(r)
        if hasattr(t, "shape") and tuple(t.shape) != arr.shape:
            raise ValueError("leaf shape mismatch: checkpoint %s vs target "
                             "%s" % (arr.shape, tuple(t.shape)))
        sharding = getattr(t, "sharding", None)
        if isinstance(sharding, jax.sharding.NamedSharding):
            placed.append(jax.device_put(arr, sharding))
        else:
            # leave non-mesh leaves UNcommitted (a committed single-device
            # scalar could not be mixed with mesh-sharded args under jit)
            placed.append(jax.numpy.asarray(arr, dtype=getattr(
                t, "dtype", None)))
    return jax.tree.unflatten(treedef, placed)


class CheckpointManager:
    """Rolling checkpoint manager (keep the newest `max_to_keep`) — the
    coordinated-snapshot shape of §5.3's checkpoint_notify flow, minus the
    pserver RPC: under jax.distributed every process participates in the
    same orbax save."""

    def __init__(self, directory, max_to_keep=3):
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        os.makedirs(self.directory, exist_ok=True)

    def save(self, state, step):
        path = save_checkpoint(self.directory, state, step)
        self._gc()
        return path

    def restore(self, target_state=None):
        return restore_checkpoint(self.directory, target_state)

    def all_steps(self):
        steps = []
        for name in os.listdir(self.directory):
            if name.startswith("step_"):
                try:
                    steps.append(int(name.split("_", 1)[1]))
                except ValueError:
                    continue
        return sorted(steps)

    def _gc(self):
        import shutil

        steps = self.all_steps()
        for step in steps[:-self.max_to_keep] if self.max_to_keep else []:
            shutil.rmtree(os.path.join(self.directory, "step_%d" % step),
                          ignore_errors=True)
