"""Program IR verifier (parity: the construction-time validation the
reference spreads across `OpProto`/`OperatorBase` arity checks
(framework/op_desc.cc), `InferShape`/`InferVarType` propagation
(framework/shape_inference.h) and MLIR-style per-pass IR verification).

`verify(program, level='strict'|'basic')` walks every block and checks:

  rule `unknown-op`        op type has a registered kernel, a bespoke
                           lowering, or a structural role
  rule `op-signature`      required input/output slots and attrs per the
                           op's `analysis.meta.OpMeta` (strict)
  rule `use-before-def`    every read is def-before-use within its block,
                           honoring sub-block visibility and the
                           persistable/feed/tensor-array anchors
  rule `dangling-ref`      `__fwd_op__` references resolve to live ops of
                           the SAME program, sub-block attrs are this
                           program's blocks, and every referenced var
                           resolves in (and belongs to) this program —
                           the clone invariants
  rule `dtype-mismatch`    statically inferred output dtype vs the
  rule `shape-mismatch`    declared var descriptor (strict; declared
                           space — AMP-marked ops are exempt by design)
  rule `donated-fetch`     donation safety: an inplace-promotion
                           candidate (large write-before-read
                           persistable) may not also be a fetch target,
                           and must genuinely be written before read

Violations are structured (`Violation`), and `verify_or_raise` wraps
them in a `VerifyError` carrying `program_version`, `block_idx`,
`op_idx`, `var`, `rule` (of the first violation) plus the full list.

`PassPipelineVerifier` is the per-pass harness `ir_passes.
optimize_for_execution` and `ir.apply_passes` run under
`PTPU_VERIFY_PASSES=1`: it verifies the input program, re-verifies after
every pass, and attributes any NEW violation to the offending pass by
name (telemetry `verify/{programs_checked,violations,pass_blamed}`,
trace spans `verify:<pass>`). docs/STATIC_ANALYSIS.md is the contract.
"""

from .. import flags as _flags
from ..observability import metrics as _metrics
from ..observability import tracing as _tracing
from . import meta as _meta

__all__ = ["Violation", "VerifyError", "ProgramVerifier", "verify",
           "verify_or_raise", "verify_enabled", "PassPipelineVerifier"]

LEVELS = ("basic", "strict")

# donation promotion only fires for buffers >= this many bytes
# (ir_passes._MIN_PROMOTE_BYTES — imported lazily to keep this module
# import-light; kept as a fallback mirror for direct use)
_MIN_PROMOTE_BYTES = 1 << 20


def verify_enabled():
    """True under PTPU_VERIFY_PASSES=1 — the pipeline hooks gate on this,
    so with the env unset the compile path is exactly the pre-verifier
    one."""
    return bool(_flags.env("PTPU_VERIFY_PASSES"))


class Violation:
    """One structured diagnostic. `key()` identifies the violation
    across pass applications (op indices shift as passes insert/delete
    ops, so identity is (rule, block, var, op type))."""

    __slots__ = ("rule", "message", "block_idx", "op_idx", "op_type",
                 "var")

    def __init__(self, rule, message, block_idx=None, op_idx=None,
                 op_type=None, var=None):
        self.rule = rule
        self.message = message
        self.block_idx = block_idx
        self.op_idx = op_idx
        self.op_type = op_type
        self.var = var

    def key(self):
        return (self.rule, self.block_idx, self.op_type, self.var)

    def __repr__(self):
        loc = []
        if self.block_idx is not None:
            loc.append("block %d" % self.block_idx)
        if self.op_idx is not None:
            loc.append("op %d" % self.op_idx)
        if self.op_type:
            loc.append(self.op_type)
        if self.var:
            loc.append("var %r" % self.var)
        return "[%s] %s%s" % (self.rule, self.message,
                              " (%s)" % ", ".join(loc) if loc else "")


class VerifyError(RuntimeError):
    """Raised on verification failure. Carries the first violation's
    structured fields plus the full list; `pass_name` names the pipeline
    pass that introduced the violations (None = the input program)."""

    def __init__(self, violations, program=None, pass_name=None):
        self.violations = list(violations)
        self.pass_name = pass_name
        self.program_version = getattr(program, "version", None)
        first = self.violations[0] if self.violations else \
            Violation("unknown", "no violations recorded")
        self.rule = first.rule
        self.block_idx = first.block_idx
        self.op_idx = first.op_idx
        self.var = first.var
        where = ("pass %r broke the program" % pass_name) if pass_name \
            else "program failed verification"
        super().__init__(
            "%s (version %s): %d violation(s)\n  %s"
            % (where, self.program_version, len(self.violations),
               "\n  ".join(repr(v) for v in self.violations[:8])))


class ProgramVerifier:
    """One verification walk over a Program (docstring above). `level`:
    'basic' = structural rules only; 'strict' adds signature conformance
    and static dtype/shape propagation."""

    def __init__(self, level="strict"):
        if level not in LEVELS:
            raise ValueError("verify level must be one of %r, got %r"
                             % (LEVELS, level))
        self.level = level

    # -- entry ---------------------------------------------------------
    def verify(self, program, fetch_names=None):
        """All violations found in `program` (empty list = clean).
        `fetch_names` default to the pipeline-pinned
        `program._opt_fetch_targets`; without either, the donation rules
        are skipped (fetch set unknown — same contract as the
        fetch-driven passes)."""
        if fetch_names is None:
            fetch_names = getattr(program, "_opt_fetch_targets", None)
        out = []
        op_ids = {id(op) for blk in program.blocks for op in blk.ops}
        # per-block write sets, computed ONCE (one pass over all ops):
        # the per-block "written elsewhere" union below stays O(B*N)
        # instead of re-walking every other block per block — this runs
        # after every pipeline pass under PTPU_VERIFY_PASSES=1
        block_writes = []
        for blk in program.blocks:
            names = set()
            for op in blk.ops:
                names.update(op.output_names())
            block_writes.append(names)
        for blk in program.blocks:
            writes_outside = set()
            for idx, names in enumerate(block_writes):
                if idx != blk.idx:
                    writes_outside |= names
            out.extend(self._check_block(program, blk, op_ids,
                                         block_writes[blk.idx],
                                         writes_outside))
        out.extend(self._check_donation(program, fetch_names))
        return out

    # -- per-block rules ----------------------------------------------
    def _check_block(self, program, blk, op_ids, written_in_block,
                     writes_outside):
        from ..core.lowering import _SPECIAL, _STRUCTURAL
        from ..framework import Block, Operator
        from ..ops import registry

        out = []
        # `writes_outside` = names written by ops of OTHER blocks: a
        # sub-block writes into closed-over parent names (and vice
        # versa) at an order this block cannot see — def-before-use is
        # only decidable for names whose every writer is in THIS block

        def anchored(name, v):
            """True when reading `name` needs no earlier in-block def:
            state (persistable), feeds (is_data, or never written
            anywhere — supplied by the feed dict), tensor arrays (the
            first mention IS the empty array), or writes in other blocks
            (order unknown — conservative)."""
            if v is not None and (v.persistable or v.is_data
                                  or getattr(v, "is_tensor_array",
                                             False)):
                return True
            return name in writes_outside \
                or name not in written_in_block

        produced = set()
        for i, op in enumerate(blk.ops):
            is_grad = "__fwd_op__" in op.attrs
            # rule unknown-op --------------------------------------------
            if not is_grad and op.type not in _STRUCTURAL \
                    and op.type not in _SPECIAL \
                    and not registry.has(op.type):
                out.append(Violation(
                    "unknown-op",
                    "op type %r has no registered kernel, bespoke "
                    "lowering, or structural role" % op.type,
                    blk.idx, i, op.type))
                produced.update(op.output_names())
                continue
            # rule dangling-ref ------------------------------------------
            for k, a in op.attrs.items():
                if isinstance(a, Operator) and id(a) not in op_ids:
                    out.append(Violation(
                        "dangling-ref",
                        "attr %r references an op (%s) that is not in "
                        "this program — grad ops must point at live "
                        "forward ops of the SAME program (clone "
                        "invariant)" % (k, a.type),
                        blk.idx, i, op.type))
                elif isinstance(a, Block) and (
                        a.idx >= len(program.blocks)
                        or program.blocks[a.idx] is not a):
                    out.append(Violation(
                        "dangling-ref",
                        "attr %r references a sub-block that is not "
                        "this program's block %d" % (k, a.idx),
                        blk.idx, i, op.type))
            for direction, slots in (("input", op.inputs),
                                     ("output", op.outputs)):
                for slot, vs in slots.items():
                    for v in vs:
                        if blk._find_var_recursive(v.name) is None:
                            out.append(Violation(
                                "dangling-ref",
                                "%s %s[%r] -> var %r is not declared in "
                                "this block or an ancestor"
                                % (direction, op.type, slot, v.name),
                                blk.idx, i, op.type, v.name))
                        elif v.block.program is not program:
                            out.append(Violation(
                                "dangling-ref",
                                "%s %s[%r] -> var %r belongs to a "
                                "DIFFERENT program (clone invariant: "
                                "a cloned op must reference the "
                                "clone's vars)"
                                % (direction, op.type, slot, v.name),
                                blk.idx, i, op.type, v.name))
            # rule use-before-def ----------------------------------------
            for name in op.input_names():
                if name in produced:
                    continue
                v = blk._find_var_recursive(name)
                if anchored(name, v):
                    continue
                out.append(Violation(
                    "use-before-def",
                    "op reads %r before any op of this block defines "
                    "it (first definition comes later in program "
                    "order)" % name,
                    blk.idx, i, op.type, name))
            # strict: signature + meta propagation -----------------------
            if self.level == "strict" and not is_grad:
                out.extend(self._check_meta(blk, i, op))
            produced.update(op.output_names())
        return out

    def _check_meta(self, blk, i, op):
        m = _meta.meta_of(op.type)
        if m is None:
            return []
        out = []
        # rule op-signature ----------------------------------------------
        for slot in m.ins:
            if not op.inputs.get(slot):
                out.append(Violation(
                    "op-signature",
                    "required input slot %r is missing or empty" % slot,
                    blk.idx, i, op.type))
        for slot in m.outs:
            if not op.outputs.get(slot):
                out.append(Violation(
                    "op-signature",
                    "required output slot %r is missing or empty" % slot,
                    blk.idx, i, op.type))
        for key in m.attrs:
            if key not in op.attrs:
                out.append(Violation(
                    "op-signature",
                    "required attr %r is missing" % key,
                    blk.idx, i, op.type))
        if out or m.infer is None:
            return out
        # rules dtype-mismatch / shape-mismatch --------------------------
        in_metas = {slot: [_meta.var_meta(blk._find_var_recursive(v.name))
                           for v in vs]
                    for slot, vs in op.inputs.items()}
        try:
            inferred = m.infer(op, in_metas)
        except ValueError as e:
            return [Violation(
                "shape-mismatch",
                "input shapes are statically incompatible: %s" % e,
                blk.idx, i, op.type)]
        except Exception:
            return []  # meta rule choked on an exotic attr: no verdict
        # AMP-marked ops deliberately run low precision under fp32
        # declarations (docs/MIXED_PRECISION.md) — declared-space dtype
        # reasoning does not apply to them
        amp_marked = bool(op.attrs.get("__amp_bf16__"))
        for slot, metas in (inferred or {}).items():
            declared = op.outputs.get(slot, [])
            for v, (shape, dtype) in zip(declared, metas):
                want_shape, want_dtype = _meta.var_meta(
                    blk._find_var_recursive(v.name))
                if dtype is not None and want_dtype is not None \
                        and dtype != want_dtype and not amp_marked:
                    out.append(Violation(
                        "dtype-mismatch",
                        "%s[%r] infers dtype %s but var %r is declared "
                        "%s" % (op.type, slot, dtype, v.name,
                                want_dtype),
                        blk.idx, i, op.type, v.name))
                if shape is not None and want_shape is not None:
                    if len(shape) != len(want_shape) or any(
                            a is not None and b is not None and a != b
                            for a, b in zip(shape, want_shape)):
                        out.append(Violation(
                            "shape-mismatch",
                            "%s[%r] infers shape %r but var %r is "
                            "declared %r" % (op.type, slot, shape,
                                             v.name, want_shape),
                            blk.idx, i, op.type, v.name))
        return out

    # -- donation safety ----------------------------------------------
    def _check_donation(self, program, fetch_names):
        """The PR-2/PR-3 convention, made checkable: an inplace-promotion
        candidate (a persistable the step writes whose OLD value no step
        op reads, large enough to promote) is donated with its input
        synthesized — so it may not also be a fetch target, and its
        first write must genuinely precede every read (docs/
        COMPILER_PASSES.md enable_inplace)."""
        if fetch_names is None:
            return []
        try:
            from ..ir_passes import _MIN_PROMOTE_BYTES as min_bytes
        except Exception:
            min_bytes = _MIN_PROMOTE_BYTES
        import numpy as np

        blk = program.global_block()
        first_write, first_read = {}, {}
        for i, op in enumerate(blk.ops):
            for n in op.input_names():
                first_read.setdefault(n, i)
            for n in op.output_names():
                first_write.setdefault(n, i)
        out = []
        fetch_set = set(fetch_names)
        for name, w in first_write.items():
            v = blk._find_var_recursive(name)
            if v is None or not v.persistable:
                continue
            r = first_read.get(name)
            if r is not None and r <= w:
                continue  # read-before-write: classified mut, never
                # promoted — standard donated state is safe (XLA copy
                # insertion protects held fetches, async_engine.py)
            if v.shape is None or any(int(d) < 0 for d in v.shape):
                continue
            try:
                from ..framework import dtype_to_np

                nbytes = int(np.prod(v.shape)) * np.dtype(
                    dtype_to_np(v.dtype)).itemsize
            except Exception:
                continue
            if nbytes < min_bytes:
                continue
            if name in fetch_set:
                out.append(Violation(
                    "donated-fetch",
                    "persistable %r is an inplace-promotion candidate "
                    "(write-before-read, %d bytes) AND a fetch target — "
                    "a donated buffer may not be fetched (the promoted "
                    "input is synthesized, not the scope value)"
                    % (name, nbytes),
                    blk.idx, first_write[name], blk.ops[w].type, name))
        return out


def verify(program, level="strict", fetch_names=None):
    """All violations in `program` (empty list = clean). See
    ProgramVerifier for the rules and `level` semantics."""
    return ProgramVerifier(level).verify(program, fetch_names)


def verify_or_raise(program, level="strict", fetch_names=None,
                    pass_name=None):
    violations = verify(program, level, fetch_names)
    if violations:
        raise VerifyError(violations, program, pass_name)
    return program


# ---------------------------------------------------------------------------
# per-pass pipeline harness (PTPU_VERIFY_PASSES=1)
# ---------------------------------------------------------------------------


class PassPipelineVerifier:
    """Blame-assigning wrapper around one pass-pipeline application.

        pv = PassPipelineVerifier(program, fetch_names)   # raises if the
                                                          # INPUT is bad
        for name in pass_names:
            get_pass(name).apply(program, scope)
            pv.after_pass(name, program)   # raises VerifyError blaming
                                           # `name` on any NEW violation

    Pre-existing violations (same rule/block/var/op-type key) are carried
    forward, never re-blamed. Telemetry: `verify/programs_checked` per
    walk, `verify/violations` per violation found, `verify/pass_blamed`
    per blamed pass; spans `verify:input` / `verify:<pass>`."""

    def __init__(self, program, fetch_names=None, level="strict",
                 raise_on_input=True):
        self._verifier = ProgramVerifier(level)
        self._fetch_names = fetch_names
        with _tracing.span("verify:input"):
            baseline = self._run(program)
        self._seen = {v.key() for v in baseline}
        if baseline and raise_on_input:
            raise VerifyError(baseline, program, pass_name=None)

    def _run(self, program):
        violations = self._verifier.verify(program, self._fetch_names)
        if _metrics.enabled():
            _metrics.counter("verify/programs_checked").inc()
            # inc(0) materializes the counter: CI gates `== 0` through
            # --assert-max, which needs the metric present in the dump
            _metrics.counter("verify/violations").inc(len(violations))
        return violations

    def after_pass(self, name, program):
        """Verify `program` post-`name`; raise VerifyError blaming `name`
        for any violation not present before it ran."""
        with _tracing.span("verify:" + name):
            violations = self._run(program)
        new = [v for v in violations if v.key() not in self._seen]
        self._seen |= {v.key() for v in violations}
        if new:
            if _metrics.enabled():
                _metrics.counter("verify/pass_blamed").inc()
            raise VerifyError(new, program, pass_name=name)
        return program


def maybe_verify(program, fetch_names=None, where="compile"):
    """Executor-side hook for compile paths that skip the pass pipeline
    (PTPU_NO_PROGRAM_OPT=1): one full verification when
    PTPU_VERIFY_PASSES=1, a no-op otherwise."""
    if not verify_enabled():
        return program
    with _tracing.span("verify:" + where):
        violations = ProgramVerifier("strict").verify(program, fetch_names)
    if _metrics.enabled():
        _metrics.counter("verify/programs_checked").inc()
        _metrics.counter("verify/violations").inc(len(violations))
    if violations:
        raise VerifyError(violations, program, pass_name=None)
    return program
