"""Static op metadata: required slots/attrs + shape/dtype inference.

Parity: the reference validates every op at CONSTRUCTION time —
`OpProto` pins required inputs/outputs/attrs (framework/op_desc.cc) and
`InferShape`/`InferVarType` propagate shapes and dtypes through the
graph before anything executes (framework/shape_inference.h). TPU-native
kernels are shape-polymorphic jax functions, so nothing forces that
declaration discipline at build time; this module restores it as
ANALYSIS metadata: each kernel contributes an optional `OpMeta` entry
(via `ops.registry.register(infer_meta=...)` at registration, or
`declare()` here for kernels that predate the verifier), and
`paddle_tpu.analysis.verifier` checks every program op against it.

An `OpMeta` carries:
  ins / outs    required input / output slot names (signature
                conformance — a missing/empty required slot is a
                violation)
  attrs         required attr keys
  infer         optional `infer(op, in_metas) -> {slot: [meta, ...]}`
                where a meta is `(shape, dtype)`: shape is a tuple with
                `None` for unknown dims (declared -1 batch dims) or None
                when fully unknown; dtype is a canonical dtype string or
                None. The verifier compares the inferred metas against
                the DECLARED output vars and reports op index, var name,
                expected vs found.

Inference runs in DECLARED space (the var descriptors), not runtime
space: jax's x64 canonicalization and the deliberate AMP divergence
(amp_rewrite marks ops `__amp_bf16__` and lets runtime values run
bfloat16 under fp32 declarations) are invisible to it — rules must
return None (unknown) wherever declared-space reasoning cannot pin the
value, and the verifier skips dtype checks on AMP-marked ops.
"""

import numpy as np

from ..framework import convert_dtype
from ..ops import registry

__all__ = ["OpMeta", "declare", "meta_of", "var_meta", "broadcast_dims",
           "align_y_to_x", "elementwise_out_dims"]


class OpMeta:
    """Signature + inference metadata for one op type (docstring above)."""

    __slots__ = ("ins", "outs", "attrs", "infer")

    def __init__(self, ins=(), outs=(), attrs=(), infer=None):
        self.ins = tuple(ins)
        self.outs = tuple(outs)
        self.attrs = tuple(attrs)
        self.infer = infer


def declare(op_type, ins=(), outs=(), attrs=(), infer=None):
    """Attach an OpMeta to an already-registered kernel (skipped silently
    when the kernel is absent — op modules are allowed to be trimmed)."""
    if not registry.has(op_type):
        return None
    return registry.set_infer_meta(op_type,
                                   OpMeta(ins, outs, attrs, infer))


def meta_of(op_type):
    """The OpMeta for `op_type`, or None (unregistered op types are the
    verifier's unknown-op rule, not this lookup's concern)."""
    if not registry.has(op_type):
        return None
    m = registry.get(op_type).infer_meta
    if m is None:
        return None
    if not isinstance(m, OpMeta):
        # a bare infer function handed to register(infer_meta=...)
        m = OpMeta(infer=m)
    return m


# ---------------------------------------------------------------------------
# (shape, dtype) helpers shared by the rules
# ---------------------------------------------------------------------------


def var_meta(v):
    """Declared (shape, dtype) of a Variable: -1 dims become None."""
    if v is None:
        return (None, None)
    shape = None
    if v.shape is not None:
        shape = tuple(None if int(d) < 0 else int(d) for d in v.shape)
    return (shape, v.dtype or None)


def _in0(in_metas, slot):
    ms = in_metas.get(slot) or []
    return ms[0] if ms else (None, None)


def broadcast_dims(xs, ys):
    """Numpy-broadcast two shape tuples with None = unknown. Returns the
    merged shape, or raises ValueError on a definite incompatibility
    (both dims known, neither 1, different)."""
    if xs is None or ys is None:
        return None
    n = max(len(xs), len(ys))
    xs = (None,) * (n - len(xs)) + tuple(xs)
    ys = (None,) * (n - len(ys)) + tuple(ys)
    out = []
    for a, b in zip(xs, ys):
        if a is None or b is None:
            # a known non-1 dim survives broadcasting against anything
            # compatible; 1-vs-unknown stays unknown
            known = a if a is not None else b
            out.append(known if known is not None and known != 1 else None)
        elif a == b or b == 1:
            out.append(a)
        elif a == 1:
            out.append(b)
        else:
            raise ValueError("dims %r and %r do not broadcast" % (a, b))
    return tuple(out)


def align_y_to_x(xs, ys, axis):
    """Fluid elementwise axis alignment: pad Y's dims to X's rank
    starting at `axis` (ops.registry.broadcast_to_axis, on shapes).
    Returns None when the alignment is impossible (ranks don't fit)."""
    if axis in (-1, None) or xs is None or ys is None:
        return ys
    if axis + len(ys) <= len(xs):
        return (1,) * axis + tuple(ys) + (1,) * (len(xs) - axis
                                                 - len(ys))
    return None


def elementwise_out_dims(xs, ys, axis):
    """Out shape of one elementwise op (axis alignment + numpy
    broadcast) in None-for-unknown space — THE shared rule: the
    `layers._elementwise` builder declares with it (translating -1) and
    the verifier infers with it, so the two can never drift (the
    declaration-drift bug class the verifier exists to catch). Raises
    ValueError on a definite incompatibility."""
    return broadcast_dims(xs, align_y_to_x(xs, ys, axis))


def _same_dtype(*metas):
    dts = {dt for _, dt in metas if dt is not None}
    return dts.pop() if len(dts) == 1 else None


# ---------------------------------------------------------------------------
# inference rules
# ---------------------------------------------------------------------------


def _identity(op, in_metas, slot="X"):
    """Out mirrors X (the unary elementwise family)."""
    return {"Out": [_in0(in_metas, slot)]}


def _elementwise(op, in_metas):
    xs, xdt = _in0(in_metas, "X")
    ys, ydt = _in0(in_metas, "Y")
    # raises on definite mismatch
    shape = elementwise_out_dims(xs, ys, op.attrs.get("axis", -1))
    # mixed declared dtypes promote at runtime (AMP O2 gray flows rely on
    # it) — only a matching pair pins the out dtype
    return {"Out": [(shape, _same_dtype((xs, xdt), (ys, ydt)))]}


def _dot_dtype(op, *metas):
    """Output dtype of a dot-class op: int32 when the quant_rewrite pass
    marked it `__quant_int8__` (int8 operands accumulate in int32 — the
    one DELIBERATE declared-space dtype change of the int8 path), the
    matching operand dtype otherwise."""
    if op.attrs.get("__quant_int8__"):
        return "int32"
    return _same_dtype(*metas)


def _mul(op, in_metas):
    xs, xdt = _in0(in_metas, "X")
    ys, ydt = _in0(in_metas, "Y")
    shape = None
    if xs is not None and ys is not None:
        xn = int(op.attrs.get("x_num_col_dims", 1))
        yn = int(op.attrs.get("y_num_col_dims", 1))
        if 0 < xn <= len(xs) and 0 < yn < len(ys) + 1:
            kx = [d for d in xs[xn:]]
            ky = [d for d in ys[:yn]]
            if None not in kx and None not in ky and \
                    int(np.prod(kx or [1])) != int(np.prod(ky or [1])):
                raise ValueError(
                    "contraction dims %r x %r do not agree" % (kx, ky))
            shape = tuple(xs[:xn]) + tuple(ys[yn:])
    return {"Out": [(shape, _dot_dtype(op, (xs, xdt), (ys, ydt)))]}


def _matmul(op, in_metas):
    xs, xdt = _in0(in_metas, "X")
    ys, ydt = _in0(in_metas, "Y")
    shape = None
    if xs is not None and ys is not None and len(xs) == 2 and len(ys) == 2:
        m = xs[1] if op.attrs.get("transpose_X") else xs[0]
        kx = xs[0] if op.attrs.get("transpose_X") else xs[1]
        ky = ys[1] if op.attrs.get("transpose_Y") else ys[0]
        n = ys[0] if op.attrs.get("transpose_Y") else ys[1]
        if kx is not None and ky is not None and kx != ky:
            raise ValueError(
                "contraction dims %r and %r do not agree" % (kx, ky))
        shape = (m, n)
    return {"Out": [(shape, _dot_dtype(op, (xs, xdt), (ys, ydt)))]}


def _cast(op, in_metas):
    xs, _ = _in0(in_metas, "X")
    return {"Out": [(xs, convert_dtype(op.attrs["out_dtype"]))]}


def _fill_shape_dtype(op, in_metas):
    shape = tuple(int(s) for s in op.attrs["shape"])
    return {"Out": [(shape, convert_dtype(op.attrs.get("dtype",
                                                       "float32")))]}


def _mean(op, in_metas):
    _, dt = _in0(in_metas, "X")
    return {"Out": [((1,), dt)]}


def _reduce(op, in_metas):
    xs, dt = _in0(in_metas, "X")
    shape = None
    if xs is not None:
        keep = bool(op.attrs.get("keep_dim", False))
        if op.attrs.get("reduce_all", False):
            shape = (1,) * len(xs) if keep else (1,)
        else:
            dim = op.attrs.get("dim", [0])
            dims = dim if isinstance(dim, (list, tuple)) else [dim]
            axes = {int(d) % len(xs) for d in dims}
            shape = tuple(1 if i in axes else d
                          for i, d in enumerate(xs)
                          if keep or i not in axes)
            if not shape:
                shape = (1,)
    return {"Out": [(shape, dt)]}


def _sum(op, in_metas):
    metas = in_metas.get("X") or [(None, None)]
    shape = metas[0][0]
    for s, _ in metas[1:]:
        try:
            shape = broadcast_dims(shape, s)
        except ValueError:
            raise
    return {"Out": [(shape, _same_dtype(*metas))]}


def _square_error_cost(op, in_metas):
    xs, dt = _in0(in_metas, "X")
    ys, _ = _in0(in_metas, "Label")
    return {"Out": [(broadcast_dims(xs, ys), dt)]}


# -- quant op family (ops/quant_ops.py + quant_rewrite; their dtype
# changes are DELIBERATE and declared here so PTPU_VERIFY_PASSES=1
# verifies quantized programs instead of tripping on them) -------------------


def _quantize_out(op, in_metas):
    xs, _ = _in0(in_metas, "Input")
    return {"Output": [(xs, "int8")]}


def _dequantize_out(op, in_metas):
    xs, _ = _in0(in_metas, "Input")
    od = op.attrs.get("out_dtype")
    return {"Output": [(xs, convert_dtype(od) if od is not None
                        else "float32")]}


def _requantize_out(op, in_metas):
    xs, _ = _in0(in_metas, "Input")
    return {"Output": [(xs, "int8")]}


def _fake_quant(op, in_metas, scale_shape=(1,)):
    """fake_quantize_*: Out mirrors X (quantize-dequantize stays in the
    input dtype); OutScale is the collected range."""
    xs, dt = _in0(in_metas, "X")
    return {"Out": [(xs, dt)], "OutScale": [(scale_shape, dt)]}


def _fake_quant_channel(op, in_metas):
    xs, dt = _in0(in_metas, "X")
    cs = (xs[0],) if xs else None
    return {"Out": [(xs, dt)], "OutScale": [(cs, dt)]}


def _fake_dequant(op, in_metas):
    xs, dt = _in0(in_metas, "X")
    return {"Out": [(xs, dt)]}


def _fused_int8_matmul(op, in_metas):
    """quant_rewrite's fused dense layer: fp32 activation × int8 weight
    with in-kernel quantize/dequantize — output is fp32 at the shape of
    the dot it replaced (matmul's (M, N), or mul's flatten-and-restore
    shape when x_num_col_dims rides the attrs). The dtype round-trip
    stays INSIDE the op — the one declared-space difference from the
    3-op chain."""
    xs, _ = _in0(in_metas, "X")
    ys, _ = _in0(in_metas, "Y")
    shape = None
    xn = op.attrs.get("x_num_col_dims")
    if xs is not None and ys is not None:
        if xn is not None:
            yn = int(op.attrs.get("y_num_col_dims", 1))
            kx = [d for d in xs[int(xn):]]
            ky = [d for d in ys[:yn]]
            if None not in kx and None not in ky and \
                    int(np.prod(kx or [1])) != int(np.prod(ky or [1])):
                raise ValueError(
                    "contraction dims %r x %r do not agree" % (kx, ky))
            shape = tuple(xs[:int(xn)]) + tuple(ys[yn:])
        elif len(xs) == 2 and len(ys) == 2:
            if xs[1] is not None and ys[0] is not None \
                    and xs[1] != ys[0]:
                raise ValueError(
                    "contraction dims %r and %r do not agree"
                    % (xs[1], ys[0]))
            shape = (xs[0], ys[1])
    return {"Out": [(shape, "float32")]}


def _lookup_table_host(op, in_metas):
    """Host-embedding lookups (sync and prefetched variants): Out is the
    Ids shape (trailing 1 squeezed, the kernel's convention) extended by
    the table's embedding dim. The dim lives on the live table registry,
    not the graph — verification without the table yields no verdict on
    the shape."""
    ids_s, _ = _in0(in_metas, "Ids")
    shape = None
    if ids_s is not None:
        s = tuple(ids_s)
        if len(s) > 1 and s[-1] == 1:
            s = s[:-1]
        from ..parallel.host_embedding import _TABLES

        table = _TABLES.get(op.attrs.get("table_name"))
        if table is not None:
            shape = s + (table.dim,)
    return {"Out": [(shape, "float32")]}


def _register_quant_metas():
    declare("quantize", ins=("Input",), outs=("Output",),
            infer=_quantize_out)
    declare("fused_int8_matmul", ins=("X", "Y", "Scale"), outs=("Out",),
            attrs=("act_scale",), infer=_fused_int8_matmul)
    declare("dequantize", ins=("Input",), outs=("Output",),
            infer=_dequantize_out)
    declare("dequantize_linear", ins=("Input", "Scale"),
            outs=("Output",), infer=_dequantize_out)
    declare("requantize", ins=("Input",), outs=("Output",),
            infer=_requantize_out)
    declare("fake_quantize_abs_max", ins=("X",),
            outs=("Out", "OutScale"), infer=_fake_quant)
    declare("fake_channel_wise_quantize_abs_max", ins=("X",),
            outs=("Out", "OutScale"), infer=_fake_quant_channel)
    for name in ("fake_quantize_range_abs_max",
                 "fake_quantize_moving_average_abs_max",
                 "fake_quantize_dequantize_moving_average_abs_max",
                 "moving_average_abs_max_scale"):
        declare(name, ins=("X", "InScale"), outs=("Out", "OutScale"),
                infer=_fake_quant)
    declare("fake_dequantize_max_abs", ins=("X", "Scale"), outs=("Out",),
            infer=_fake_dequant)
    declare("fake_channel_wise_dequantize_max_abs", ins=("X", "Scales"),
            outs=("Out",), infer=_fake_dequant)


def _register_builtin_metas():
    for name in ("elementwise_add", "elementwise_sub", "elementwise_mul",
                 "elementwise_div", "elementwise_max", "elementwise_min",
                 "elementwise_pow"):
        declare(name, ins=("X", "Y"), outs=("Out",), infer=_elementwise)
    for name in ("relu", "tanh", "sigmoid", "exp", "sqrt", "rsqrt", "abs",
                 "square", "softmax", "scale", "sign", "softsign",
                 "softplus", "ceil", "floor", "round", "reciprocal"):
        declare(name, ins=("X",), outs=("Out",), infer=_identity)
    declare("mul", ins=("X", "Y"), outs=("Out",), infer=_mul)
    declare("matmul", ins=("X", "Y"), outs=("Out",), infer=_matmul)
    declare("cast", ins=("X",), outs=("Out",), attrs=("out_dtype",),
            infer=_cast)
    declare("fill_constant", outs=("Out",), attrs=("shape",),
            infer=_fill_shape_dtype)
    declare("assign_value", outs=("Out",), attrs=("shape", "values"),
            infer=_fill_shape_dtype)
    declare("fill_zeros_like", ins=("X",), outs=("Out",), infer=_identity)
    declare("mean", ins=("X",), outs=("Out",), infer=_mean)
    for name in ("reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
                 "reduce_prod"):
        declare(name, ins=("X",), outs=("Out",), infer=_reduce)
    declare("sum", ins=("X",), outs=("Out",), infer=_sum)
    declare("square_error_cost", ins=("X", "Label"), outs=("Out",),
            infer=_square_error_cost)
    declare("dropout", ins=("X",), outs=("Out",),
            infer=lambda op, m: {"Out": [_in0(m, "X")]})
    declare("fused_elemwise_activation", ins=("X", "Y"), outs=("Out",),
            attrs=("functor_list",))
    declare("fill_constant_batch_size_like", ins=("Input",), outs=("Out",),
            attrs=("shape",))
    declare("lookup_table", ins=("Ids", "W"), outs=("Out",))
    declare("lookup_table_host", ins=("Ids", "Anchor"), outs=("Out",),
            attrs=("table_name",), infer=_lookup_table_host)
    declare("lookup_table_prefetched",
            ins=("Ids", "Anchor", "Rows", "Inv"), outs=("Out",),
            attrs=("table_name",), infer=_lookup_table_host)
    declare("concat", ins=("X",), outs=("Out",))
    declare("reshape", ins=("X",), outs=("Out",))
    declare("transpose", ins=("X",), outs=("Out",), attrs=("axis",))
    declare("layer_norm", ins=("X",), outs=("Y",))
    declare("batch_norm", ins=("X", "Scale", "Bias", "Mean", "Variance"),
            outs=("Y",))
    declare("conv2d", ins=("Input", "Filter"), outs=("Output",))
    declare("conv2d_fusion", ins=("Input", "Filter"), outs=("Output",))
    declare("cross_entropy", ins=("X", "Label"), outs=("Y",))
    declare("softmax_with_cross_entropy", ins=("Logits", "Label"),
            outs=("Loss",))
    declare("sgd", ins=("Param", "Grad", "LearningRate"),
            outs=("ParamOut",))
    declare("adam", ins=("Param", "Grad", "LearningRate",
                         "Moment1", "Moment2", "Beta1Pow", "Beta2Pow"),
            outs=("ParamOut", "Moment1Out", "Moment2Out"))


_register_builtin_metas()
_register_quant_metas()
