"""Static analysis over the Program IR (docs/STATIC_ANALYSIS.md).

`verify(program)` checks a Program against the construction-time
invariants the reference enforced through `OpProto` arity checks and
`InferShape`/`InferVarType` propagation; `PTPU_VERIFY_PASSES=1` makes
every compile path run it before the pass pipeline and after each pass,
blaming the pass that broke an invariant (`ir_passes.
optimize_for_execution`, `ir.apply_passes`, and the no-opt compile
paths all route through the same hook). The repo-invariant linter that
rides with it lives in `tools/ptpu_lint.py`.

`concurrency` is the runtime sibling for the THREADED runtime: tracked
lock/condition factories (`make_lock`/`make_rlock`/`make_condition`,
plain primitives unless `PTPU_LOCK_CHECK=1`), a lock-order/deadlock
detector in the Eraser/TSan spirit, blocking-while-holding and
long-hold rules, and the violation/telemetry surface the CI `race`
stage gates on.
"""

from .meta import OpMeta, declare, meta_of, var_meta
from .verifier import (PassPipelineVerifier, ProgramVerifier, VerifyError,
                       Violation, maybe_verify, verify, verify_enabled,
                       verify_or_raise)
from . import concurrency
from .concurrency import (LockCheckError, LockViolation, TrackedCondition,
                          TrackedLock, TrackedRLock, make_condition,
                          make_lock, make_rlock)

__all__ = [
    "OpMeta", "declare", "meta_of", "var_meta",
    "PassPipelineVerifier", "ProgramVerifier", "VerifyError", "Violation",
    "maybe_verify", "verify", "verify_enabled", "verify_or_raise",
    "concurrency", "LockCheckError", "LockViolation", "TrackedCondition",
    "TrackedLock", "TrackedRLock", "make_condition", "make_lock",
    "make_rlock",
]
