"""Concurrency analysis for the threaded runtime (docs/STATIC_ANALYSIS.md)
— the lockset/lock-order sibling of the Program IR verifier, in the
spirit of Eraser (Savage et al., SOSP '97) and ThreadSanitizer
(Serebryany & Iskhodzhanov, WBIA '09): instead of waiting for an unlucky
interleaving to actually deadlock a CI run, every tracked acquisition
feeds a process-global lock-order graph and a *potential* deadlock (a
cycle in that graph) is reported the first time both orders have been
observed — even when the run never hangs.

The surface is a factory, not a subclass zoo:

    from paddle_tpu.analysis.concurrency import make_lock, make_condition

    self._lock = make_lock("serving.kv_pool")
    self._cv   = make_condition("serving.engine.cv")

With ``PTPU_LOCK_CHECK`` unset (the default) the factories return the
PLAIN ``threading`` primitives — zero overhead, behaviorally identical,
the ``PTPU_VERIFY_PASSES`` identity pattern (pinned by test). With
``PTPU_LOCK_CHECK=1`` they return ``TrackedLock`` / ``TrackedRLock`` /
``TrackedCondition`` wrappers that record, per thread, the set of held
locks plus a cheap acquisition stack, and check on every acquisition:

  rule ``lock-order-cycle``      acquiring B while holding A adds edge
                                 A->B to the global order graph; a cycle
                                 reports both acquisition stacks, lock
                                 names and thread names
  rule ``self-deadlock``         UNTIMED blocking re-acquire of a
                                 non-reentrant lock the same thread
                                 already holds (raised, since
                                 proceeding would hang; timed/
                                 non-blocking probes keep their plain
                                 semantics)
  rule ``same-class-nesting``    acquiring a second instance of a lock
                                 class while holding one — the
                                 class-level order graph cannot order
                                 instances, so the opposite nesting
                                 elsewhere would be an undetectable
                                 ABBA (the lockdep rule)
  rule ``blocking-while-holding``a ``Condition.wait`` while holding a
                                 *different* tracked lock, or any
                                 declared blocking region
                                 (``blocking_region`` wraps ``queue``
                                 waits and device syncs) entered with a
                                 tracked lock held
  rule ``long-hold``             a lock held longer than
                                 ``PTPU_LOCK_HOLD_MS`` milliseconds
                                 (unset = off)
  rule ``pool-invariant`` /      runtime invariant hooks
  rule ``engine-invariant``      (``KVBlockPool.check_invariants``, the
                                 serving engine's step-boundary checks)
                                 report through the same channel

Violations are structured (:class:`LockViolation`, the PR-8 `Violation`
shape), accumulated in the tracker (``violations()``), surfaced once as
a ``RuntimeWarning``, and countable by CI: ``publish_metrics()`` writes
``concurrency/{locks_tracked,acquisitions,order_edges,violations,
max_hold_ms}`` into the observability registry (the ``race`` CI stage
gates ``concurrency/violations == 0``). ``assert_clean()`` raises
:class:`LockCheckError` (the `VerifyError` shape) for tests.

Lock NAMES are stable per site ("serving.kv_pool", "dist.pserver.opt",
...), not per instance: the order graph reasons about lock *classes*,
which is what makes cross-instance ABBA observable at all. Name a new
lock after its subsystem and role; two different roles must never share
a name (docs/STATIC_ANALYSIS.md "how to name a lock").

This module must import nothing heavier than ``paddle_tpu.flags`` at
module level: converted modules create locks inside constructors with a
function-level import, and observability falls back to plain locks if
asked during interpreter bootstrap.
"""

import atexit
import sys
import threading
import time

from .. import flags as _flags

__all__ = [
    "LockCheckError", "LockViolation", "TrackedCondition", "TrackedLock",
    "TrackedRLock", "assert_clean", "blocking_region", "check_blocking",
    "make_condition", "make_lock", "make_rlock", "publish_metrics",
    "record_violation", "reset", "stats", "tracker", "tracking_enabled",
    "violations",
]

_OWN_FILE = __file__


def tracking_enabled():
    """True under PTPU_LOCK_CHECK=1 — read at CALL time, so the factory
    decides per lock creation (the env-unset path never builds a
    tracker)."""
    return bool(_flags.env("PTPU_LOCK_CHECK"))


# ---------------------------------------------------------------------------
# structured diagnostics (the PR-8 Violation / VerifyError shape)
# ---------------------------------------------------------------------------


class LockViolation:
    """One structured concurrency diagnostic. ``locks``/``threads`` name
    every lock and thread involved; ``stacks`` carries the formatted
    acquisition stacks (also embedded in ``message``). ``key()`` is the
    dedup identity — each distinct hazard reports once; ``detail``
    distinguishes different hazards that share a lock set (e.g. two
    different pool-invariant breaks on the same pool — without it the
    second would be silently swallowed)."""

    __slots__ = ("rule", "message", "locks", "threads", "stacks",
                 "detail")

    def __init__(self, rule, message, locks=(), threads=(), stacks=(),
                 detail=None):
        self.rule = rule
        self.message = message
        self.locks = tuple(locks)
        self.threads = tuple(threads)
        self.stacks = tuple(stacks)
        self.detail = detail

    def key(self):
        return (self.rule, tuple(sorted(self.locks)), self.detail)

    def __repr__(self):
        loc = []
        if self.locks:
            loc.append("locks %s" % ", ".join(self.locks))
        if self.threads:
            loc.append("threads %s" % ", ".join(self.threads))
        return "[%s] %s%s" % (self.rule, self.message,
                              " (%s)" % "; ".join(loc) if loc else "")


class LockCheckError(RuntimeError):
    """Raised by ``assert_clean()`` (and on a would-hang self-deadlock).
    Carries the first violation's structured fields plus the full list —
    the `VerifyError` shape."""

    def __init__(self, violations):
        self.violations = list(violations)
        first = self.violations[0] if self.violations else \
            LockViolation("unknown", "no violations recorded")
        self.rule = first.rule
        self.locks = first.locks
        self.threads = first.threads
        super().__init__(
            "concurrency check failed: %d violation(s)\n  %s"
            % (len(self.violations),
               "\n  ".join(repr(v) for v in self.violations[:8])))


def _capture_stack(limit=16):
    """Cheap acquisition stack: a raw frame walk (no linecache I/O —
    traceback.extract_stack costs ~100x more and this runs per
    acquisition under the flag). Frames inside this module are elided."""
    try:
        f = sys._getframe(1)
    except ValueError:  # pragma: no cover
        return ()
    out = []
    while f is not None and len(out) < limit:
        code = f.f_code
        if code.co_filename != _OWN_FILE:
            out.append((code.co_filename, f.f_lineno, code.co_name))
        f = f.f_back
    return tuple(out)


def _fmt_stack(stack, indent="      "):
    if not stack:
        return indent + "<no stack captured>"
    return "\n".join("%s%s:%d in %s" % (indent, fn, ln, fname)
                     for fn, ln, fname in stack)


class _Held:
    """One tracked lock a thread currently holds."""

    __slots__ = ("lock", "stack", "t0", "depth")

    def __init__(self, lock, stack, t0):
        self.lock = lock
        self.stack = stack
        self.t0 = t0
        self.depth = 1


class _EdgeInfo:
    """First observation of lock-order edge a -> b: who held a (and
    where it was acquired) when b was acquired (and where)."""

    __slots__ = ("thread", "stack_from", "stack_to")

    def __init__(self, thread, stack_from, stack_to):
        self.thread = thread
        self.stack_from = stack_from
        self.stack_to = stack_to


class LockTracker:
    """Process-global lock accounting: per-thread held sets, the lock
    order graph, violation accumulation. Internal state is guarded by a
    RAW ``threading.Lock`` — the tracker must never wait on a lock it
    tracks."""

    def __init__(self):
        self._mu = threading.Lock()          # raw on purpose
        self._tls = threading.local()
        self._locks_tracked = 0
        self._acquisitions = 0
        self._max_hold_ms = 0.0
        self._edges = {}        # (a, b) -> _EdgeInfo, first observation
        self._adj = {}          # a -> set of b
        self._violations = []
        self._seen_keys = set()

    # -- per-thread held list ------------------------------------------
    def _held(self):
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def held_names(self):
        """Names of the tracked locks the CALLING thread holds now."""
        return [h.lock.name for h in self._held()]

    # -- registration / acquisition ------------------------------------
    def register(self, lock):
        with self._mu:
            self._locks_tracked += 1

    def on_acquired(self, lock):
        held = self._held()
        for h in held:
            if h.lock is lock:
                h.depth += 1
                return
        stack = _capture_stack()
        now = time.perf_counter()
        with self._mu:
            self._acquisitions += 1
        for h in held:
            if h.lock.name != lock.name:
                self._add_edge(h, lock, stack)
            elif h.lock is not lock:
                # a SECOND instance of the same lock class nested inside
                # the first (two pools, two metric locks, ...): the
                # class-level order graph cannot order instances, so the
                # opposite nesting elsewhere would be an undetectable
                # ABBA — report the nesting itself (the lockdep rule:
                # a lock class nested within itself needs an explicit
                # instance order)
                self.record(LockViolation(
                    "same-class-nesting",
                    "thread %r acquires a second %r instance while "
                    "holding one — cross-instance order is undefined "
                    "(potential ABBA the class-level graph cannot "
                    "see)\n    first instance acquired at:\n%s\n"
                    "    second instance acquired at:\n%s"
                    % (threading.current_thread().name, lock.name,
                       _fmt_stack(h.stack), _fmt_stack(stack)),
                    locks=(lock.name,),
                    threads=(threading.current_thread().name,),
                    stacks=(_fmt_stack(h.stack), _fmt_stack(stack))))
        held.append(_Held(lock, stack, now))

    def check_self_deadlock(self, lock):
        """Called BEFORE a blocking acquire of a non-reentrant lock: a
        re-acquire by the holder would hang forever, so report and raise
        instead of deadlocking the process."""
        for h in self._held():
            if h.lock is lock:
                v = LockViolation(
                    "self-deadlock",
                    "thread %r re-acquires non-reentrant lock %r it "
                    "already holds — this would deadlock\n"
                    "    first acquired at:\n%s\n    re-acquired at:\n%s"
                    % (threading.current_thread().name, lock.name,
                       _fmt_stack(h.stack), _fmt_stack(_capture_stack())),
                    locks=(lock.name,),
                    threads=(threading.current_thread().name,),
                    stacks=(_fmt_stack(h.stack),
                            _fmt_stack(_capture_stack())))
                self.record(v)
                raise LockCheckError([v])

    def on_release(self, lock):
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            h = held[i]
            if h.lock is lock:
                h.depth -= 1
                if h.depth == 0:
                    del held[i]
                    self._note_hold(lock, time.perf_counter() - h.t0,
                                    h.stack)
                return
        # releasing a lock this thread never tracked as held (e.g.
        # acquired before tracking reset): nothing to account

    def _note_hold(self, lock, dt, stack):
        ms = dt * 1000.0
        threshold = _flags.env("PTPU_LOCK_HOLD_MS")
        with self._mu:
            if ms > self._max_hold_ms:
                self._max_hold_ms = ms
        if threshold is not None and ms > float(threshold):
            self.record(LockViolation(
                "long-hold",
                "lock %r held %.1f ms (> PTPU_LOCK_HOLD_MS=%s) by thread "
                "%r\n    acquired at:\n%s"
                % (lock.name, ms, threshold,
                   threading.current_thread().name, _fmt_stack(stack)),
                locks=(lock.name,),
                threads=(threading.current_thread().name,),
                stacks=(_fmt_stack(stack),)))

    # -- condition-wait bookkeeping ------------------------------------
    def pause_held(self, lock):
        """``Condition.wait`` is about to release ``lock`` (fully, even
        for an RLock — ``_release_save`` drops every recursion level).
        Pop its held entry so hold-time and blocking checks see the
        truth; returns the entry for ``resume_held``."""
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i].lock is lock:
                h = held.pop(i)
                self._note_hold(lock, time.perf_counter() - h.t0, h.stack)
                return h
        return None

    def resume_held(self, lock, entry):
        if entry is None:
            return
        entry.t0 = time.perf_counter()
        self._held().append(entry)

    def check_blocking(self, kind, site, exclude=None):
        """A blocking operation (queue/cond wait, device sync) is about
        to run on the calling thread: holding any tracked lock other
        than ``exclude`` across it is a liveness hazard."""
        others = [h for h in self._held() if h.lock is not exclude]
        if not others:
            return
        names = tuple(h.lock.name for h in others)
        self.record(LockViolation(
            "blocking-while-holding",
            "thread %r blocks on %s%s while holding tracked lock(s) %s\n"
            "    blocking at:\n%s\n    holding %r acquired at:\n%s"
            % (threading.current_thread().name, kind,
               " (%s)" % site if site else "", ", ".join(names),
               _fmt_stack(_capture_stack()), names[0],
               _fmt_stack(others[0].stack)),
            # locks holds LOCK names only (the documented contract);
            # the blocking site keys the dedup via detail instead
            locks=names,
            threads=(threading.current_thread().name,),
            stacks=(_fmt_stack(_capture_stack()),
                    _fmt_stack(others[0].stack)),
            detail=(kind, site)))

    # -- order graph ----------------------------------------------------
    def _add_edge(self, held_entry, lock, stack_to):
        a, b = held_entry.lock.name, lock.name
        tname = threading.current_thread().name
        with self._mu:
            if (a, b) in self._edges:
                return
            self._edges[(a, b)] = _EdgeInfo(tname, held_entry.stack,
                                            stack_to)
            self._adj.setdefault(a, set()).add(b)
            cycle = self._find_path(b, a)
        if cycle is not None:
            self._report_cycle(a, b, held_entry, stack_to, cycle)

    def _find_path(self, src, dst):
        """Holding _mu: a path src -> ... -> dst in the order graph, or
        None. Iterative DFS — the graph holds lock CLASSES, so it stays
        tiny."""
        stack, seen = [(src, (src,))], {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._adj.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + (nxt,)))
        return None

    def _report_cycle(self, a, b, held_entry, stack_to, path):
        # path is b -> ... -> a; closing edge a -> b makes the cycle
        cycle_names = (a,) + path
        rev = self._edges.get((path[0], path[1])) if len(path) > 1 \
            else None
        tname = threading.current_thread().name
        msg = [
            "potential deadlock: lock-order cycle %s"
            % " -> ".join(cycle_names),
            "    thread %r holds %r, acquired at:" % (tname, a),
            _fmt_stack(held_entry.stack),
            "    and acquires %r at:" % b,
            _fmt_stack(stack_to),
        ]
        if rev is not None:
            msg += [
                "    conflicting order: thread %r held %r, acquired at:"
                % (rev.thread, path[0]),
                _fmt_stack(rev.stack_from),
                "    and acquired %r at:" % path[1],
                _fmt_stack(rev.stack_to),
            ]
        threads = (tname,) + ((rev.thread,) if rev is not None else ())
        self.record(LockViolation(
            "lock-order-cycle", "\n".join(msg),
            locks=tuple(dict.fromkeys(cycle_names)),
            threads=tuple(dict.fromkeys(threads)),
            stacks=(_fmt_stack(held_entry.stack), _fmt_stack(stack_to))
            + ((_fmt_stack(rev.stack_from), _fmt_stack(rev.stack_to))
               if rev is not None else ())))

    # -- violation accumulation ----------------------------------------
    def record(self, violation):
        with self._mu:
            if violation.key() in self._seen_keys:
                return
            self._seen_keys.add(violation.key())
            self._violations.append(violation)
        import warnings

        warnings.warn("PTPU_LOCK_CHECK: %r" % violation, RuntimeWarning,
                      stacklevel=2)
        # no publish() here: record() can run inside an acquisition
        # callback, and publishing touches the (tracked) metrics-registry
        # lock — the atexit hook, the engine invariant hook and explicit
        # publish_metrics() calls flush the gauges instead

    def violations(self):
        with self._mu:
            return list(self._violations)

    def stats(self):
        with self._mu:
            return {
                "locks_tracked": self._locks_tracked,
                "acquisitions": self._acquisitions,
                "order_edges": len(self._edges),
                "violations": len(self._violations),
                "max_hold_ms": self._max_hold_ms,
            }

    def reset(self):
        with self._mu:
            self._locks_tracked = 0
            self._acquisitions = 0
            self._max_hold_ms = 0.0
            self._edges.clear()
            self._adj.clear()
            del self._violations[:]
            self._seen_keys.clear()
        self._tls = threading.local()

    def publish(self):
        """Write the counters into the observability registry (gauges,
        so re-publishing is idempotent): ``concurrency/*`` rows in
        docs/OBSERVABILITY.md. Explicit registry use — the race CI
        stage dumps these via PTPU_METRICS_OUT."""
        try:
            from ..observability import metrics as _metrics
        except ImportError:  # pragma: no cover - interpreter teardown
            return
        snap = self.stats()
        reg = _metrics.registry()
        reg.gauge("concurrency/locks_tracked").set(snap["locks_tracked"])
        reg.gauge("concurrency/acquisitions").set(snap["acquisitions"])
        reg.gauge("concurrency/order_edges").set(snap["order_edges"])
        reg.gauge("concurrency/violations").set(snap["violations"])
        reg.gauge("concurrency/max_hold_ms").set(snap["max_hold_ms"])


# ---------------------------------------------------------------------------
# tracked primitives
# ---------------------------------------------------------------------------


class TrackedLock:
    """Drop-in ``threading.Lock`` recording held-set membership and
    order-graph edges. ``name`` is the stable per-site lock class."""

    _reentrant = False

    def __init__(self, name, tracker_=None, raw=None):
        """``raw`` adopts an existing primitive of the matching kind
        (used by TrackedCondition to wrap a caller-supplied plain lock —
        the flag-off path accepts any lock there, so the flag-on path
        must too)."""
        self.name = str(name)
        self._tracker = tracker_ or tracker()
        self._raw = raw if raw is not None else self._make_raw()
        self._tracker.register(self)

    def _make_raw(self):
        return threading.Lock()

    def acquire(self, blocking=True, timeout=-1):
        # the self-deadlock guard fires only for UNTIMED blocking
        # re-acquires — the case that would hang forever. A timed
        # acquire by the holder legitimately returns False after the
        # wait under plain threading, and the wrappers may not change
        # behavior
        if blocking and timeout == -1 and not self._reentrant:
            self._tracker.check_self_deadlock(self)
        got = self._raw.acquire(blocking, timeout)
        if got:
            self._tracker.on_acquired(self)
        return got

    def release(self):
        self._tracker.on_release(self)
        self._raw.release()

    def locked(self):
        return self._raw.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return "<%s %r>" % (type(self).__name__, self.name)


class TrackedRLock(TrackedLock):
    """Drop-in ``threading.RLock``: re-acquisition by the holder bumps
    the held entry's depth — no self-deadlock check, no new edges."""

    _reentrant = True

    def _make_raw(self):
        return threading.RLock()

    def locked(self):
        # drop-in parity: RLock grows locked() in Python 3.12 —
        # delegate where it exists, raise AttributeError where the
        # plain primitive would have none
        raw = getattr(self._raw, "locked", None)
        if raw is None:
            raise AttributeError(
                "RLock.locked() is not available on this Python")
        return raw()


class TrackedCondition:
    """Drop-in ``threading.Condition`` over a tracked lock (default: a
    fresh ``TrackedRLock``, matching ``threading.Condition()``'s default
    RLock). ``wait`` checks blocking-while-holding against every OTHER
    tracked lock the thread holds, and pauses the held entry for the
    duration (the lock genuinely is released while waiting)."""

    def __init__(self, name, lock=None, tracker_=None):
        self.name = str(name)
        self._tracker = tracker_ or tracker()
        if lock is None:
            lock = TrackedRLock(name, self._tracker)
        elif not isinstance(lock, TrackedLock):
            # a caller-supplied PLAIN primitive (legal with the flag
            # off, so legal here too): adopt it as the tracked lock's
            # raw — reentrant wrapper iff it is an RLock
            cls = TrackedLock if isinstance(
                lock, type(threading.Lock())) else TrackedRLock
            lock = cls(name, self._tracker, raw=lock)
        self._lock = lock
        self._cond = threading.Condition(self._lock._raw)

    # -- lock surface --------------------------------------------------
    def acquire(self, *args, **kwargs):
        return self._lock.acquire(*args, **kwargs)

    def release(self):
        self._lock.release()

    def __enter__(self):
        self._lock.acquire()
        return self

    def __exit__(self, *exc):
        self._lock.release()
        return False

    # -- condition surface ---------------------------------------------
    def wait(self, timeout=None):
        self._tracker.check_blocking("Condition.wait", self.name,
                                     exclude=self._lock)
        entry = self._tracker.pause_held(self._lock)
        try:
            return self._cond.wait(timeout)
        finally:
            self._tracker.resume_held(self._lock, entry)

    def wait_for(self, predicate, timeout=None):
        endtime = None
        waittime = timeout
        result = predicate()
        while not result:
            if waittime is not None:
                if endtime is None:
                    endtime = time.monotonic() + waittime
                else:
                    waittime = endtime - time.monotonic()
                    if waittime <= 0:
                        break
            self.wait(waittime)
            result = predicate()
        return result

    def notify(self, n=1):
        self._cond.notify(n)

    def notify_all(self):
        self._cond.notify_all()

    def __repr__(self):
        return "<TrackedCondition %r>" % self.name


# ---------------------------------------------------------------------------
# the factory + module-level surface
# ---------------------------------------------------------------------------

_TRACKER = None
_TRACKER_MU = threading.Lock()


def tracker():
    """The process-global :class:`LockTracker`, created on first use."""
    global _TRACKER
    if _TRACKER is None:
        with _TRACKER_MU:
            if _TRACKER is None:
                t = LockTracker()
                atexit.register(t.publish)
                _TRACKER = t
    return _TRACKER


def make_lock(name):
    """A mutex named ``name``: ``threading.Lock()`` when
    ``PTPU_LOCK_CHECK`` is unset (identity), else a
    :class:`TrackedLock`."""
    if not tracking_enabled():
        return threading.Lock()
    return TrackedLock(name)


def make_rlock(name):
    if not tracking_enabled():
        return threading.RLock()
    return TrackedRLock(name)


def make_condition(name, lock=None):
    """A condition variable named ``name``: ``threading.Condition(lock)``
    when ``PTPU_LOCK_CHECK`` is unset, else a
    :class:`TrackedCondition` (over a tracked RLock by default, matching
    the plain Condition's default)."""
    if not tracking_enabled():
        return threading.Condition(lock)
    return TrackedCondition(name, lock=lock)


class _NullRegion:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_REGION = _NullRegion()


def blocking_region(kind, site=""):
    """Declare a blocking operation (``queue.get``, ``Semaphore``
    acquire, a forced device sync): entering it with any tracked lock
    held records a ``blocking-while-holding`` violation. No-op (a shared
    null context, zero allocation) when tracking is off."""
    t = _TRACKER
    if t is None:
        return _NULL_REGION
    t.check_blocking(kind, site)
    return _NULL_REGION


def check_blocking(kind, site=""):
    """Imperative form of :func:`blocking_region` for call sites where a
    context manager is awkward (e.g. inside a loop body)."""
    t = _TRACKER
    if t is not None:
        t.check_blocking(kind, site)


def record_violation(rule, message, locks=(), threads=None, stacks=(),
                     detail=None):
    """Report a violation through the tracker (the runtime invariant
    hooks use this). ``detail`` keys apart different hazards sharing a
    lock set — pass the invariant/check name so each distinct failure
    reports once instead of the first one shadowing the rest. No-op
    when tracking never started."""
    t = _TRACKER
    if t is None:
        return None
    if threads is None:
        threads = (threading.current_thread().name,)
    v = LockViolation(rule, message, locks=locks, threads=threads,
                      stacks=stacks, detail=detail)
    t.record(v)
    return v


def violations():
    """Accumulated violations (empty when tracking never started)."""
    t = _TRACKER
    return t.violations() if t is not None else []


def assert_clean():
    """Raise :class:`LockCheckError` if any violation accumulated."""
    vs = violations()
    if vs:
        # passive flight-recorder hook: concurrency must stay importable
        # without observability, and must never fail a clean process by
        # failing to dump a dirty one
        blackbox = sys.modules.get(
            "paddle_tpu.observability.flight_recorder")
        if blackbox is not None:
            try:
                blackbox.record_event("lock_check_failed",
                                      violations=len(vs),
                                      first=str(vs[0]))
                blackbox.dump("lock_check_failed")
            except Exception:
                pass
        raise LockCheckError(vs)


def stats():
    t = _TRACKER
    return t.stats() if t is not None else {
        "locks_tracked": 0, "acquisitions": 0, "order_edges": 0,
        "violations": 0, "max_hold_ms": 0.0}


def publish_metrics():
    """Write the ``concurrency/*`` gauges into the observability
    registry now (also runs at process exit once a tracker exists)."""
    t = _TRACKER
    if t is not None:
        t.publish()


def reset():
    """Clear tracked state (tests). Locks already created stay tracked
    by the same tracker; counters, edges and violations start over."""
    t = _TRACKER
    if t is not None:
        t.reset()
