"""SE-ResNeXt (parity: the reference's distributed-test flagship CNN,
tests/unittests/dist_se_resnext.py — grouped 1-3-1 bottlenecks with
squeeze-and-excitation gates; the model the reference uses to validate
multi-GPU/pserver training at CNN scale).

Built entirely from the layers DSL: grouped conv (cardinality) lowers to
XLA's feature-group convolution, the SE gate is two tiny FCs around a
global pool — all fused by XLA, no bespoke kernels.
"""

from .. import layers


def _conv_bn(x, ch_out, filter_size, stride, padding, act="relu",
             groups=1, is_test=False):
    conv = layers.conv2d(input=x, num_filters=ch_out,
                         filter_size=filter_size, stride=stride,
                         padding=padding, groups=groups, act=None,
                         bias_attr=False)
    return layers.batch_norm(input=conv, act=act, is_test=is_test)


def squeeze_excitation(x, num_channels, reduction_ratio=16):
    pool = layers.pool2d(input=x, pool_type="avg", global_pooling=True)
    squeeze = layers.fc(input=pool, size=num_channels // reduction_ratio,
                        act="relu")
    excitation = layers.fc(input=squeeze, size=num_channels, act="sigmoid")
    gate = layers.reshape(excitation, shape=[-1, num_channels, 1, 1])
    return layers.elementwise_mul(x, gate, axis=0)


def bottleneck_block(x, num_filters, stride, cardinality=32,
                     reduction_ratio=16, is_test=False):
    ch_in = x.shape[1]
    conv0 = _conv_bn(x, num_filters, 1, 1, 0, is_test=is_test)
    conv1 = _conv_bn(conv0, num_filters, 3, stride, 1, groups=cardinality,
                     is_test=is_test)
    conv2 = _conv_bn(conv1, num_filters * 2, 1, 1, 0, act=None,
                     is_test=is_test)
    scaled = squeeze_excitation(conv2, num_filters * 2, reduction_ratio)
    if ch_in != num_filters * 2 or stride != 1:
        short = _conv_bn(x, num_filters * 2, 1, stride, 0, act=None,
                         is_test=is_test)
    else:
        short = x
    return layers.relu(layers.elementwise_add(short, scaled))


def se_resnext(input, class_dim, depth=50, cardinality=32,
               reduction_ratio=16, is_test=False):
    cfg = {50: [3, 4, 6, 3], 101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}[depth]
    num_filters = [128, 256, 512, 1024]
    x = _conv_bn(input, 64, 7, 2, 3, is_test=is_test)
    x = layers.pool2d(input=x, pool_size=3, pool_stride=2, pool_padding=1,
                      pool_type="max")
    for block, n in enumerate(cfg):
        for i in range(n):
            x = bottleneck_block(
                x, num_filters[block], stride=2 if i == 0 and block != 0
                else 1, cardinality=cardinality,
                reduction_ratio=reduction_ratio, is_test=is_test)
    pool = layers.pool2d(input=x, pool_type="avg", global_pooling=True)
    drop = layers.dropout(x=pool, dropout_prob=0.2, is_test=is_test)
    return layers.fc(input=drop, size=class_dim, act="softmax")


def build(class_dim=10, depth=50, img_shape=(3, 32, 32), is_test=False):
    """Declare data vars + network; returns (img, label, pred, loss, acc)
    (dist_se_resnext.py get_model shape)."""
    img = layers.data(name="img", shape=list(img_shape), dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="int64")
    predict = se_resnext(img, class_dim, depth=depth, is_test=is_test)
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=predict, label=label)
    return img, label, predict, avg_cost, acc
