"""Seq2seq NMT with attention (parity: benchmark/fluid/models/
machine_translation.py — GRU encoder/decoder + Bahdanau-style attention,
teacher-forced training).

TPU design: the reference builds the decoder with DynamicRNN over LoD
batches; here the whole decoder is one `dynamic_gru` pass plus a batched
attention matmul over padded [B, T] inputs with length masks — a single
fused XLA computation rather than per-step scopes (SURVEY §5.7)."""

from .. import layers


def encoder(src_word, src_len, dict_size, embed_dim, hidden_dim):
    emb = layers.embedding(input=src_word, size=[dict_size, embed_dim])
    proj = layers.fc(input=emb, size=hidden_dim * 3, num_flatten_dims=2,
                     bias_attr=False)
    enc = layers.dynamic_gru(input=proj, size=hidden_dim)
    return enc


def attention(dec_state, enc_states, enc_mask):
    """Additive-free dot attention: scores = dec_state @ enc^T, masked
    softmax over source positions, context = weights @ enc."""
    # dec_state [B, Td, H]; enc_states [B, Ts, H]
    scores = layers.matmul(dec_state, enc_states, transpose_y=True)
    # mask [B, Ts] -> [B, 1, Ts]
    mask = layers.unsqueeze(enc_mask, axes=[1])
    big_neg = layers.scale(mask, scale=-1e9, bias=1e9)  # 0 where valid
    scores = layers.elementwise_add(scores, big_neg)
    weights = layers.softmax(scores)
    return layers.matmul(weights, enc_states)


def build(src_dict_size=10000, trg_dict_size=10000, embed_dim=512,
          hidden_dim=512, max_len=50):
    src = layers.data(name="src_word", shape=[max_len], dtype="int64")
    src_len = layers.data(name="src_len", shape=[1], dtype="int64")
    trg = layers.data(name="trg_word", shape=[max_len], dtype="int64")
    trg_next = layers.data(name="trg_next", shape=[max_len], dtype="int64")
    trg_len = layers.data(name="trg_len", shape=[1], dtype="int64")

    enc = encoder(src, src_len, src_dict_size, embed_dim, hidden_dim)
    src_mask = layers.cast(
        layers.sequence_mask(src_len, maxlen=max_len, dtype="float32"),
        "float32")

    trg_emb = layers.embedding(input=trg, size=[trg_dict_size, embed_dim])
    dec_proj = layers.fc(input=trg_emb, size=hidden_dim * 3,
                         num_flatten_dims=2, bias_attr=False)
    dec = layers.dynamic_gru(input=dec_proj, size=hidden_dim)

    ctxt = attention(dec, enc, src_mask)
    dec_ctx = layers.concat([dec, ctxt], axis=2)
    logits = layers.fc(input=dec_ctx, size=trg_dict_size, num_flatten_dims=2)

    # masked token cross-entropy over the padded target
    flat_logits = layers.reshape(logits, shape=[-1, trg_dict_size])
    flat_label = layers.reshape(trg_next, shape=[-1, 1])
    cost = layers.softmax_with_cross_entropy(logits=flat_logits,
                                             label=flat_label)
    cost = layers.reshape(cost, shape=[-1, max_len])
    trg_mask = layers.cast(
        layers.sequence_mask(trg_len, maxlen=max_len, dtype="float32"),
        "float32")
    masked = layers.elementwise_mul(cost, trg_mask)
    total = layers.reduce_sum(masked)
    denom = layers.reduce_sum(trg_mask)
    avg_cost = layers.elementwise_div(total, denom)
    return (src, src_len, trg, trg_next, trg_len), logits, avg_cost


def build_beam_decoder(dict_size=30000, word_dim=16, decoder_size=32,
                       beam_size=2, max_length=8, src_len=8, end_id=1):
    """Port of the reference book test's While-loop beam decoder — the
    level-2-LoD workload (tests/book/test_machine_translation.py
    decoder_decode :85-150: init_ids/init_scores arrive as lod_level=2
    tensors, per-step state flows through array_read/array_write,
    sequence_expand replicates state across beam lanes, beam_search prunes
    and beam_search_decode backtracks).

    TPU-native layout: the LoD beam lanes become a dense [batch, beam]
    axis (the documented level-2 mapping, docs/MIGRATING.md) — lane
    replication is a broadcast instead of sequence_expand, beam reordering
    is a one_hot(parent) matmul instead of LoD row shuffling, and the
    whole While body is one jitted region. Feeds: `bd_src` [batch,
    src_len] int64, `bd_init_ids` [batch, beam] int64, `bd_init_scores`
    [batch, beam] float32 (the test builds the latter two from the
    reference's level-2 LoDTensors host-side). Returns (sentence ids
    [batch, beam, T], sentence scores [batch, beam])."""
    from ..param_attr import ParamAttr

    src = layers.data(name="bd_src", shape=[src_len], dtype="int64")
    init_ids = layers.data(name="bd_init_ids", shape=[beam_size],
                           dtype="int64")
    init_scores = layers.data(name="bd_init_scores", shape=[beam_size],
                              dtype="float32")

    # encoder context (reference: LSTM last step; here mean + tanh fc)
    src_emb = layers.embedding(src, size=[dict_size, word_dim],
                               param_attr=ParamAttr(name="bd_vemb"))
    pooled = layers.reduce_mean(src_emb, dim=1)
    context = layers.fc(pooled, decoder_size, act="tanh",
                        param_attr=ParamAttr(name="bd_enc_w"),
                        bias_attr=ParamAttr(name="bd_enc_b"))

    counter = layers.zeros(shape=[1], dtype="int64")
    array_len = layers.fill_constant(shape=[1], dtype="int64",
                                     value=max_length)

    # beam lanes exist from step 0 (init_scores = [0, -inf, ...] keeps
    # step 1 expanding only lane 0, the reference's single-row init)
    state0 = layers.expand(layers.unsqueeze(context, axes=[1]),
                           expand_times=[1, beam_size, 1])

    state_array = layers.array_write(state0, counter)
    ids_array = layers.array_write(init_ids, counter)
    scores_array = layers.array_write(init_scores, counter)
    zero_parent = layers.cast(
        layers.zeros_like(init_ids), "int32")
    parents_array = layers.array_write(zero_parent, counter)

    cond = layers.less_than(x=counter, y=array_len)
    # max_trip_count sizes the in-graph tensor-array buffers (the
    # reference's dynamic While grows LoD arrays; here capacity is static)
    loop = layers.While(cond=cond, max_trip_count=max_length)
    with loop.block():
        pre_ids = layers.array_read(ids_array, counter)
        pre_state = layers.array_read(state_array, counter)
        pre_score = layers.array_read(scores_array, counter)

        ids_emb = layers.embedding(pre_ids, size=[dict_size, word_dim],
                                   param_attr=ParamAttr(name="bd_vemb_dec"))
        cat = layers.concat([pre_state, ids_emb], axis=2)
        cur_state = layers.fc(cat, decoder_size, act="tanh",
                              num_flatten_dims=2,
                              param_attr=ParamAttr(name="bd_dec_w"),
                              bias_attr=ParamAttr(name="bd_dec_b"))
        cur_score = layers.fc(cur_state, dict_size, act="softmax",
                              num_flatten_dims=2,
                              param_attr=ParamAttr(name="bd_out_w"),
                              bias_attr=ParamAttr(name="bd_out_b"))
        topk_scores, topk_idx = layers.topk(cur_score, k=beam_size)
        sel_ids, sel_scores, parent = layers.beam_search(
            pre_ids, pre_score, topk_idx, topk_scores, beam_size, end_id)
        # beam reorder (reference: LoD row shuffle): one_hot(parent) matmul
        perm = layers.one_hot(parent, beam_size)  # [B, beam, beam(old)]
        new_state = layers.matmul(perm, cur_state)

        layers.increment(counter, value=1, in_place=True)
        layers.array_write(sel_ids, counter, array=ids_array)
        layers.array_write(sel_scores, counter, array=scores_array)
        layers.array_write(new_state, counter, array=state_array)
        layers.array_write(parent, counter, array=parents_array)
        layers.less_than(x=counter, y=array_len, cond=cond)

    # stack decode steps 1..max_length into [T, batch, beam] (the init
    # slot 0 holds the bos seed, not a decoded step)
    def read_at(arr, t):
        idx = layers.fill_constant(shape=[1], dtype="int64", value=t)
        return layers.array_read(arr, idx)

    step_ids = layers.stack(
        [read_at(ids_array, t) for t in range(1, max_length + 1)], axis=0)
    step_scores = layers.stack(
        [read_at(scores_array, t) for t in range(1, max_length + 1)], axis=0)
    step_parents = layers.stack(
        [read_at(parents_array, t) for t in range(1, max_length + 1)],
        axis=0)
    sent_ids, sent_scores = layers.beam_search_decode(
        step_ids, step_scores, step_parents, beam_size=beam_size,
        end_id=end_id)
    return (src, init_ids, init_scores), sent_ids, sent_scores
