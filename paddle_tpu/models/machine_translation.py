"""Seq2seq NMT with attention (parity: benchmark/fluid/models/
machine_translation.py — GRU encoder/decoder + Bahdanau-style attention,
teacher-forced training).

TPU design: the reference builds the decoder with DynamicRNN over LoD
batches; here the whole decoder is one `dynamic_gru` pass plus a batched
attention matmul over padded [B, T] inputs with length masks — a single
fused XLA computation rather than per-step scopes (SURVEY §5.7)."""

from .. import layers


def encoder(src_word, src_len, dict_size, embed_dim, hidden_dim):
    emb = layers.embedding(input=src_word, size=[dict_size, embed_dim])
    proj = layers.fc(input=emb, size=hidden_dim * 3, num_flatten_dims=2,
                     bias_attr=False)
    enc = layers.dynamic_gru(input=proj, size=hidden_dim)
    return enc


def attention(dec_state, enc_states, enc_mask):
    """Additive-free dot attention: scores = dec_state @ enc^T, masked
    softmax over source positions, context = weights @ enc."""
    # dec_state [B, Td, H]; enc_states [B, Ts, H]
    scores = layers.matmul(dec_state, enc_states, transpose_y=True)
    # mask [B, Ts] -> [B, 1, Ts]
    mask = layers.unsqueeze(enc_mask, axes=[1])
    big_neg = layers.scale(mask, scale=-1e9, bias=1e9)  # 0 where valid
    scores = layers.elementwise_add(scores, big_neg)
    weights = layers.softmax(scores)
    return layers.matmul(weights, enc_states)


def build(src_dict_size=10000, trg_dict_size=10000, embed_dim=512,
          hidden_dim=512, max_len=50):
    src = layers.data(name="src_word", shape=[max_len], dtype="int64")
    src_len = layers.data(name="src_len", shape=[1], dtype="int64")
    trg = layers.data(name="trg_word", shape=[max_len], dtype="int64")
    trg_next = layers.data(name="trg_next", shape=[max_len], dtype="int64")
    trg_len = layers.data(name="trg_len", shape=[1], dtype="int64")

    enc = encoder(src, src_len, src_dict_size, embed_dim, hidden_dim)
    src_mask = layers.cast(
        layers.sequence_mask(src_len, maxlen=max_len, dtype="float32"),
        "float32")

    trg_emb = layers.embedding(input=trg, size=[trg_dict_size, embed_dim])
    dec_proj = layers.fc(input=trg_emb, size=hidden_dim * 3,
                         num_flatten_dims=2, bias_attr=False)
    dec = layers.dynamic_gru(input=dec_proj, size=hidden_dim)

    ctxt = attention(dec, enc, src_mask)
    dec_ctx = layers.concat([dec, ctxt], axis=2)
    logits = layers.fc(input=dec_ctx, size=trg_dict_size, num_flatten_dims=2)

    # masked token cross-entropy over the padded target
    flat_logits = layers.reshape(logits, shape=[-1, trg_dict_size])
    flat_label = layers.reshape(trg_next, shape=[-1, 1])
    cost = layers.softmax_with_cross_entropy(logits=flat_logits,
                                             label=flat_label)
    cost = layers.reshape(cost, shape=[-1, max_len])
    trg_mask = layers.cast(
        layers.sequence_mask(trg_len, maxlen=max_len, dtype="float32"),
        "float32")
    masked = layers.elementwise_mul(cost, trg_mask)
    total = layers.reduce_sum(masked)
    denom = layers.reduce_sum(trg_mask)
    avg_cost = layers.elementwise_div(total, denom)
    return (src, src_len, trg, trg_next, trg_len), logits, avg_cost
