"""MNIST models (parity: benchmark/fluid/models/mnist.py cnn_model and
tests/book/test_recognize_digits.py mlp/conv variants)."""

from .. import layers


def mlp(img, label, hidden_sizes=(128, 64)):
    """Softmax-classifier MLP (book test_recognize_digits.py `mlp`)."""
    h = img
    for size in hidden_sizes:
        h = layers.fc(input=h, size=size, act="relu")
    prediction = layers.fc(input=h, size=10, act="softmax")
    cost = layers.cross_entropy(input=prediction, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=prediction, label=label)
    return prediction, avg_cost, acc


def cnn(img, label):
    """LeNet-ish conv net (benchmark/fluid/models/mnist.py cnn_model)."""
    conv1 = layers.conv2d(input=img, num_filters=20, filter_size=5,
                          act="relu")
    pool1 = layers.pool2d(input=conv1, pool_size=2, pool_stride=2,
                          pool_type="max")
    conv2 = layers.conv2d(input=pool1, num_filters=50, filter_size=5,
                          act="relu")
    pool2 = layers.pool2d(input=conv2, pool_size=2, pool_stride=2,
                          pool_type="max")
    prediction = layers.fc(input=pool2, size=10, act="softmax",
                           num_flatten_dims=1)
    cost = layers.cross_entropy(input=prediction, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=prediction, label=label)
    return prediction, avg_cost, acc


def build(arch="mlp", img_shape=(1, 28, 28)):
    """Declare data vars + network; returns (img, label, pred, loss, acc)."""
    if arch == "mlp":
        img = layers.data(name="img", shape=[784], dtype="float32")
    else:
        img = layers.data(name="img", shape=list(img_shape), dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="int64")
    net = mlp if arch == "mlp" else cnn
    prediction, avg_cost, acc = net(img, label)
    return img, label, prediction, avg_cost, acc
