"""Flagship Transformer LM — TPU-first model math.

This is the model the benchmark + graft entry drive. Unlike the fluid-layer
DSL (which exists for API parity), the flagship is written directly as pure
JAX functions over a param pytree so the SPMD trainer
(paddle_tpu/parallel/transformer.py) can shard it with shard_map:

- weights layout chosen for the MXU: all matmuls are [*, D] x [D, *] dots in
  bfloat16 with fp32 accumulation
- attention heads on the tensor-parallel axis; sequence-parallel residual
  stream (Megatron-SP style all_gather/reduce_scatter seams are in the
  *trainer*, not here — these functions compute on whatever local shard they
  are handed)
- optional mixture-of-experts FFN (expert-parallel over the data axis)

Reference counterpart: Fluid's transformer benchmark model
(benchmark/fluid/models/machine_translation.py + dist_transformer.py) — the
capability target, not the design.
"""

import dataclasses
import functools
import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 6
    d_ff: int = 2048
    max_seq_len: int = 512
    dtype: Any = jnp.bfloat16
    # MoE: 0 experts = dense. One MoE FFN per pipeline stage when enabled.
    n_experts: int = 0
    expert_capacity_factor: float = 2.0
    dropout: float = 0.0
    tie_embeddings: bool = True
    remat: bool = True

    @property
    def head_dim(self):
        return self.d_model // self.n_heads


def init_params(key, cfg: TransformerConfig):
    """Full (unsharded) parameter pytree. Layer weights carry a leading
    [n_layers] axis so the pipeline axis can shard them directly."""
    D, H, Dh, F, L, V = (cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff,
                         cfg.n_layers, cfg.vocab_size)
    k = iter(jax.random.split(key, 16 + L))

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (1.0 / math.sqrt(fan_in))).astype(jnp.float32)

    params = {
        "embed": dense(next(k), (V, D), D),
        "pos_embed": dense(next(k), (cfg.max_seq_len, D), D),
        "final_ln_scale": jnp.ones((D,), jnp.float32),
        "final_ln_bias": jnp.zeros((D,), jnp.float32),
        "layers": {
            "ln1_scale": jnp.ones((L, D), jnp.float32),
            "ln1_bias": jnp.zeros((L, D), jnp.float32),
            "wqkv": dense(next(k), (L, D, 3, H, Dh), D),
            "wo": dense(next(k), (L, H, Dh, D), D),
            "ln2_scale": jnp.ones((L, D), jnp.float32),
            "ln2_bias": jnp.zeros((L, D), jnp.float32),
            "w1": dense(next(k), (L, D, F), D),
            "b1": jnp.zeros((L, F), jnp.float32),
            "w2": dense(next(k), (L, F, D), F),
            "b2": jnp.zeros((L, D), jnp.float32),
        },
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense(next(k), (D, V), D)
    if cfg.n_experts:
        E = cfg.n_experts
        params["moe"] = {
            "router": dense(next(k), (D, E), D),
            "w1": dense(next(k), (E, D, F), D),
            "w2": dense(next(k), (E, F, D), F),
        }
    return params


def layer_norm(x, scale, bias, eps=1e-5):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, -1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), -1, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + eps) * scale + bias
    return out.astype(x.dtype)


def causal_attention(q, k, v, seq_offset=0, use_flash=None):
    """q,k,v: [B, T, H, Dh] (H may be a tp-local slice). fp32 softmax,
    bf16 matmuls on the MXU. On block-aligned self-attention the flash
    kernel dispatcher (ops/pallas_kernels.flash_attention — library TPU
    kernel on-chip, portable Pallas kernel elsewhere) replaces the naive
    [T, T] path — O(block) VMEM instead of materializing scores in HBM."""
    B, Tq, H, Dh = q.shape
    Tk = k.shape[1]
    if use_flash is None:
        use_flash = (jax.default_backend() == "tpu" and seq_offset == 0
                     and Tq == Tk and Tq >= 256 and Dh >= 64)
    if use_flash:
        from ..ops.pallas_kernels import flash_attention

        ctx = flash_attention(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), True, 1.0 / math.sqrt(Dh))
        return ctx.transpose(0, 2, 1, 3)
    scale = 1.0 / math.sqrt(Dh)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    qpos = jnp.arange(Tq)[:, None] + seq_offset
    kpos = jnp.arange(Tk)[None, :]
    mask = qpos >= kpos
    logits = jnp.where(mask[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def attention_block(lp, h_full, dtype):
    """One attention sublayer on an already-gathered [B, T, D] input with
    tp-local head weights. Returns the *partial* output projection (caller
    reduces over tp)."""
    q, k, v = [
        jnp.einsum("btd,dhx->bthx", h_full, lp["wqkv"][:, i].astype(dtype))
        for i in range(3)
    ]
    ctx = causal_attention(q, k, v)
    return jnp.einsum("bthx,hxd->btd", ctx, lp["wo"].astype(dtype))


def ffn_block(lp, h_full, dtype):
    """Dense FFN with tp-local columns of w1 / rows of w2: returns partial
    sums for the caller to reduce."""
    a = jnp.einsum("btd,df->btf", h_full, lp["w1"].astype(dtype))
    a = jax.nn.gelu(a + lp["b1"].astype(dtype))
    return jnp.einsum("btf,fd->btd", a, lp["w2"].astype(dtype))


def embed_tokens(params, tokens, cfg):
    D = cfg.d_model
    h = jnp.take(params["embed"], tokens, axis=0).astype(cfg.dtype)
    h = h * math.sqrt(D)
    pos = params["pos_embed"][: tokens.shape[1]].astype(cfg.dtype)
    return h + pos[None]


def lm_logits(params, h, cfg):
    w = params.get("lm_head")
    if w is None:
        w = params["embed"].T
    return jnp.einsum("...d,dv->...v", h, w.astype(h.dtype),
                      preferred_element_type=jnp.float32)


def single_chip_hidden(params, tokens, cfg: TransformerConfig):
    """Embed -> layers under lax.scan (one compiled block body, optionally
    rematerialized) -> final LN. Shared by the forward (graft `entry()`)
    and the training loss so architecture changes cannot diverge."""
    h = embed_tokens(params, tokens, cfg)

    def body(h, lp):
        x = layer_norm(h, lp["ln1_scale"], lp["ln1_bias"])
        attn = attention_block(lp, x, cfg.dtype)
        h = h + attn
        x = layer_norm(h, lp["ln2_scale"], lp["ln2_bias"])
        h = h + ffn_block(lp, x, cfg.dtype)
        return h, None

    if cfg.remat:
        body = jax.checkpoint(body)
    h, _ = jax.lax.scan(body, h, params["layers"])
    return layer_norm(h, params["final_ln_scale"], params["final_ln_bias"])


def single_chip_forward(params, tokens, cfg: TransformerConfig):
    """Plain (unsharded) forward — the graft `entry()` path and single-chip
    bench."""
    return lm_logits(params, single_chip_hidden(params, tokens, cfg), cfg)


def token_cross_entropy(logits, labels):
    """Mean CE over tokens; logits fp32 [B, T, V]."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    picked = jnp.take_along_axis(logp, labels[..., None], axis=-1)
    return -jnp.mean(picked)


def single_chip_loss(params, tokens, labels, cfg):
    """Forward + chunked memory-lean CE head. The vocab head is computed
    per sequence chunk through the same custom-vjp CE the Fluid path uses
    (ops/loss_ops._hard_label_ce: residual = bf16 logits, backward
    recomputes the softmax elementwise behind a barrier) — the full-seq
    fp32 logits + log-softmax residual otherwise pin ~16G at batch 128,
    capping the batch below the MXU's preferred operating point."""
    from ..ops.loss_ops import _hard_label_ce

    h = single_chip_hidden(params, tokens, cfg)
    T = h.shape[1]
    # ~4 chunks caps the transient while keeping each vocab dot large
    # (over-chunking long sequences serializes many small dots)
    chunk = T if T <= 256 else max(256, T // 4)
    total = 0.0
    for s in range(0, T, chunk):
        logits = lm_logits(params, h[:, s:s + chunk], cfg)
        logits = logits.astype(cfg.dtype)
        total = total + _hard_label_ce(
            logits, labels[:, s:s + chunk], -100).sum()
    return total / (labels.shape[0] * labels.shape[1])


def param_count(params):
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
