"""Stacked LSTM sentiment classifier (parity:
benchmark/fluid/models/stacked_dynamic_lstm.py — embedding -> fc -> N x
dynamic_lstm -> max pools -> softmax over 2 classes).

TPU note: Fluid's LoD ragged batches become padded [B, T] int batches with
an explicit `seq_len` var; the lstm ops mask by sequence length
(SURVEY §5.7 bucketing+masking replacement for LoD).
"""

from .. import layers


def build(dict_size=30000, emb_dim=128, hid_dim=128, stacked_num=3,
          seq_len=80, class_dim=2):
    data = layers.data(name="words", shape=[seq_len], dtype="int64")
    label = layers.data(name="label", shape=[1], dtype="int64")
    lengths = layers.data(name="seq_len", shape=[1], dtype="int64")

    emb = layers.embedding(input=data, size=[dict_size, emb_dim])
    fc1 = layers.fc(input=emb, size=hid_dim * 4, num_flatten_dims=2)
    lstm1, _cell1 = layers.dynamic_lstm(input=fc1, size=hid_dim * 4)

    inputs = [fc1, lstm1]
    for _ in range(2, stacked_num + 1):
        fc = layers.fc(input=inputs, size=hid_dim * 4, num_flatten_dims=2)
        lstm, cell = layers.dynamic_lstm(input=fc, size=hid_dim * 4,
                                         is_reverse=False)
        inputs = [fc, lstm]

    fc_last = layers.sequence_pool(input=inputs[0], pool_type="max",
                                   sequence_length=lengths)
    lstm_last = layers.sequence_pool(input=inputs[1], pool_type="max",
                                     sequence_length=lengths)
    prediction = layers.fc(input=[fc_last, lstm_last], size=class_dim,
                           act="softmax")
    cost = layers.cross_entropy(input=prediction, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=prediction, label=label)
    return data, label, lengths, prediction, avg_cost, acc
