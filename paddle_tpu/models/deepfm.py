"""DeepFM CTR model (BASELINE.md config 4 — sparse-embedding CTR parity
with the reference's Downpour/pslib capability, SURVEY §2.3 P6/P7; the
giant embedding table is the part that maps to host/sharded embedding in
the distributed build).

Fields: `sparse_ids` [B, F] int64 feature ids (already hashed into one
shared vocab), `dense_x` [B, D] float features, `label` [B, 1].
FM first-order + second-order + deep MLP tower, sigmoid CTR output.
"""

from .. import layers


def build(sparse_feature_dim=int(1e5), num_fields=26, dense_dim=13,
          embed_dim=16, mlp_dims=(400, 400, 400), is_sparse=True):
    sparse_ids = layers.data(name="sparse_ids", shape=[num_fields],
                             dtype="int64")
    dense_x = layers.data(name="dense_x", shape=[dense_dim], dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="int64")

    # first-order: per-id scalar weight
    w1 = layers.embedding(input=sparse_ids, size=[sparse_feature_dim, 1],
                          is_sparse=is_sparse)
    first_order = layers.reduce_sum(w1, dim=[1, 2], keep_dim=False)
    first_order = layers.reshape(first_order, shape=[-1, 1])

    # second-order FM: 0.5 * ((sum v)^2 - sum v^2)
    emb = layers.embedding(input=sparse_ids,
                           size=[sparse_feature_dim, embed_dim],
                           is_sparse=is_sparse)  # [B, F, K]
    sum_emb = layers.reduce_sum(emb, dim=[1])            # [B, K]
    sum_sq = layers.square(sum_emb)
    sq_sum = layers.reduce_sum(layers.square(emb), dim=[1])
    second_order = layers.scale(
        layers.reduce_sum(layers.elementwise_sub(sum_sq, sq_sum), dim=[1],
                          keep_dim=True), scale=0.5)

    # deep tower over [flattened embeddings ; dense]
    deep_in = layers.concat(
        [layers.flatten(emb, axis=1), dense_x], axis=1)
    h = deep_in
    for dim in mlp_dims:
        h = layers.fc(input=h, size=dim, act="relu")
    deep_out = layers.fc(input=h, size=1, act=None)

    logit = layers.elementwise_add(
        layers.elementwise_add(first_order, second_order), deep_out)
    predict = layers.sigmoid(logit)
    cost = layers.sigmoid_cross_entropy_with_logits(
        x=logit, label=layers.cast(label, "float32"))
    avg_cost = layers.mean(cost)
    auc_var, _, _ = layers.auc(input=predict, label=label,
                               num_thresholds=2**10 - 1)
    return (sparse_ids, dense_x, label), predict, avg_cost, auc_var
