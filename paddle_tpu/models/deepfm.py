"""DeepFM CTR model (BASELINE.md config 4 — sparse-embedding CTR parity
with the reference's Downpour/pslib capability, SURVEY §2.3 P6/P7; the
giant embedding table is the part that maps to host/sharded embedding in
the distributed build).

Fields: `sparse_ids` [B, F] int64 feature ids (already hashed into one
shared vocab), `dense_x` [B, D] float features, `label` [B, 1].
FM first-order + second-order + deep MLP tower, sigmoid CTR output.
"""

from .. import layers


def build_distributed(vocab_size=int(1e4), num_fields=8, embed_dim=8,
                      mlp_dims=(32, 16), num_shards=2, learning_rate=0.1,
                      table_prefix="deepfm"):
    """DeepFM over HOST-RAM sharded embedding tables — the recommender
    fast-path shape (docs/RECOMMENDER.md): both the first-order dim-1
    table and the second-order dim-K table are `distributed_embedding`
    lookups on the SAME ids variable, so with PTPU_EMBED_PREFETCH=1 the
    prefetch pipeline stages both tables' rows one step ahead and the
    compiled step never pays an in-step host callback.

    Feeds: `ids` [B, F] int64 (pre-folded below vocab_size), `label`
    [B, 1] float32. Returns ((ids, label), predict, avg_cost)."""
    ids = layers.data(name="ids", shape=[num_fields], dtype="int64",
                      append_batch_size=False)
    label = layers.data(name="label", shape=[1], dtype="float32")

    # first-order: per-id scalar weight from a dim-1 host table
    w1 = layers.distributed_embedding(
        ids, table_name=table_prefix + "_w1", size=[vocab_size, 1],
        num_shards=num_shards, learning_rate=learning_rate)  # [B, F, 1]
    first_order = layers.reduce_sum(
        layers.reshape(w1, [-1, num_fields]), dim=[1], keep_dim=True)

    # second-order FM over the dim-K host table: 0.5*((sum v)^2 - sum v^2)
    emb = layers.distributed_embedding(
        ids, table_name=table_prefix + "_emb",
        size=[vocab_size, embed_dim], num_shards=num_shards,
        learning_rate=learning_rate)  # [B, F, K]
    sum_emb = layers.reduce_sum(emb, dim=[1])
    sq_sum = layers.reduce_sum(layers.square(emb), dim=[1])
    second_order = layers.scale(
        layers.reduce_sum(
            layers.elementwise_sub(layers.square(sum_emb), sq_sum),
            dim=[1], keep_dim=True), scale=0.5)

    # deep tower over the flattened embeddings
    h = layers.reshape(emb, [-1, num_fields * embed_dim])
    for dim in mlp_dims:
        h = layers.fc(input=h, size=dim, act="relu")
    deep_out = layers.fc(input=h, size=1, act=None)

    logit = layers.elementwise_add(
        layers.elementwise_add(first_order, second_order), deep_out)
    predict = layers.sigmoid(logit)
    cost = layers.log_loss(predict, label, epsilon=1e-6)
    avg_cost = layers.mean(cost)
    return (ids, label), predict, avg_cost


def build(sparse_feature_dim=int(1e5), num_fields=26, dense_dim=13,
          embed_dim=16, mlp_dims=(400, 400, 400), is_sparse=True):
    sparse_ids = layers.data(name="sparse_ids", shape=[num_fields],
                             dtype="int64")
    dense_x = layers.data(name="dense_x", shape=[dense_dim], dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="int64")

    # first-order: per-id scalar weight
    w1 = layers.embedding(input=sparse_ids, size=[sparse_feature_dim, 1],
                          is_sparse=is_sparse)
    first_order = layers.reduce_sum(w1, dim=[1, 2], keep_dim=False)
    first_order = layers.reshape(first_order, shape=[-1, 1])

    # second-order FM: 0.5 * ((sum v)^2 - sum v^2)
    emb = layers.embedding(input=sparse_ids,
                           size=[sparse_feature_dim, embed_dim],
                           is_sparse=is_sparse)  # [B, F, K]
    sum_emb = layers.reduce_sum(emb, dim=[1])            # [B, K]
    sum_sq = layers.square(sum_emb)
    sq_sum = layers.reduce_sum(layers.square(emb), dim=[1])
    second_order = layers.scale(
        layers.reduce_sum(layers.elementwise_sub(sum_sq, sq_sum), dim=[1],
                          keep_dim=True), scale=0.5)

    # deep tower over [flattened embeddings ; dense]
    deep_in = layers.concat(
        [layers.flatten(emb, axis=1), dense_x], axis=1)
    h = deep_in
    for dim in mlp_dims:
        h = layers.fc(input=h, size=dim, act="relu")
    deep_out = layers.fc(input=h, size=1, act=None)

    logit = layers.elementwise_add(
        layers.elementwise_add(first_order, second_order), deep_out)
    predict = layers.sigmoid(logit)
    cost = layers.sigmoid_cross_entropy_with_logits(
        x=logit, label=layers.cast(label, "float32"))
    avg_cost = layers.mean(cost)
    auc_var, _, _ = layers.auc(input=predict, label=label,
                               num_thresholds=2**10 - 1)
    return (sparse_ids, dense_x, label), predict, avg_cost, auc_var
