"""Word2vec N-gram LM (parity: tests/book/test_word2vec.py — 4 context
words -> shared embedding -> concat -> hidden -> softmax)."""

from .. import layers
from ..param_attr import ParamAttr


def build(dict_size=2073, embed_size=32, hidden_size=256, is_sparse=False):
    words = [layers.data(name=n, shape=[1], dtype="int64")
             for n in ("firstw", "secondw", "thirdw", "forthw", "nextw")]

    embs = []
    for w in words[:4]:
        emb = layers.embedding(
            input=w, size=[dict_size, embed_size], dtype="float32",
            is_sparse=is_sparse, param_attr=ParamAttr(name="shared_w"))
        embs.append(emb)

    concat_embed = layers.concat(input=embs, axis=1)
    hidden1 = layers.fc(input=concat_embed, size=hidden_size, act="sigmoid")
    predict_word = layers.fc(input=hidden1, size=dict_size, act="softmax")
    cost = layers.cross_entropy(input=predict_word, label=words[4])
    avg_cost = layers.mean(cost)
    return words, predict_word, avg_cost
