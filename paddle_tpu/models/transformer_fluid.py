"""Flagship transformer LM built ENTIRELY from the Fluid layers API
(`fluid.layers` + `nets.scaled_dot_product_attention`) — the proof that
API users get native-TPU speed through the descriptor lowering
(executor.py `_CompiledStep`: the whole program becomes ONE jitted XLA
step), not just users of the bespoke jax model in models/transformer.py.

Same architecture and scale as the native flagship (models/transformer.py,
cross-checked by tests): pre-LN decoder-only LM, vocab 32000, d_model 512,
8 heads, 6 layers, d_ff 2048 (~65M params). The TPU knobs the VERDICT asked
to surface through the API path are all exercised here:
  - AMP bf16: contrib.mixed_precision.decorate marks matmul/mul/
    flash_attention white-list ops (MXU-native bf16 operands, fp32
    accumulation), including inside recompute sub-blocks
  - remat: each encoder layer pair can run under layers.recompute —
    activation memory collapses to the segment boundary. Round 5: with
    the fused multihead-attention op + chunked CE head, batch 160 fits
    16G HBM WITHOUT remat and trains ~10% faster (286.4k vs 260.7k
    tok/s) — remat now only pays at batch > 192 or long sequences
  - fused multihead attention: nets.fused_multihead_attention keeps
    heads as real dot output dims so the flash kernel's [B,H,T,Dh]
    operand layout folds into the projection dots (the fc+split
    formulation materializes ~34 ms/step of HBM copies)
  - flash attention: nets.scaled_dot_product_attention(dropout=0) lowers
    to the fused Pallas flash kernel with causal masking

Reference parity anchor: the model zoo transformer
(/root/reference/benchmark/fluid/models/transformer.py) built on
fluid.layers; this one is decoder-only to match BASELINE.json config 3.
"""

from .. import layers, nets
from ..param_attr import ParamAttr

__all__ = ["build", "build_stacked"]


def build(vocab_size=32000, d_model=512, n_heads=8, n_layers=6, d_ff=2048,
          seq_len=512, dropout_rate=0.0, remat=True, dtype="float32",
          head_chunk=None):
    """Build the LM graph; returns (tokens, labels, mean_loss) Variables.

    Feeds: tokens int32 [B, seq_len], labels int32 [B, seq_len] (next-token
    ids). Loss = mean token cross-entropy in fp32 (matches
    models/transformer.py token_cross_entropy).

    dtype="bfloat16" stores params AND the residual stream in bf16 — the
    native flagship's precision scheme. Kernels that need fp32 keep it
    internally regardless (layer_norm stats, softmax_with_cross_entropy
    logsumexp + fp32 loss, sgd update math)."""
    tokens = layers.data(name="tokens", shape=[seq_len], dtype="int32")
    labels = layers.data(name="labels", shape=[seq_len], dtype="int32")

    h = layers.embedding(tokens, size=[vocab_size, d_model], dtype=dtype,
                         param_attr=ParamAttr(shard_spec=("tp", None)))
    h = layers.scale(h, scale=float(d_model) ** 0.5)
    h = layers.add_position_encoding(h, alpha=1.0, beta=1.0)

    # Megatron tensor-parallel plan as explicit annotations (inert on a
    # dp-only mesh — the planner drops axes the mesh lacks): qkv/fc1
    # column-split, proj/fc2 row-split; GSPMD inserts the two psums per
    # layer when CompiledProgram runs with tensor_parallel_degree > 1
    def encoder_layer(x):
        a = layers.layer_norm(x, begin_norm_axis=2)
        if not dropout_rate:
            # the fused sublayer keeps heads as real dot output dims, so
            # the flash kernel's [B,H,T,Dh] operand layout folds into the
            # projection dots instead of materializing as HBM copies
            # (~10% of step time through fc+split, measured; see
            # ops/compat_ops.py fused_multihead_attention)
            proj = nets.fused_multihead_attention(a, n_heads, causal=True)
        else:
            qkv = layers.fc(a, 3 * d_model, num_flatten_dims=2,
                            param_attr=ParamAttr(shard_spec=(None, "tp")))
            q, k, v = layers.split(qkv, num_or_sections=3, dim=-1)
            attn = nets.scaled_dot_product_attention(
                q, k, v, num_heads=n_heads, dropout_rate=dropout_rate,
                causal=True)
            proj = layers.fc(attn, d_model, num_flatten_dims=2,
                             param_attr=ParamAttr(shard_spec=("tp", None)))
            proj = layers.dropout(proj, dropout_prob=dropout_rate)
        x = layers.elementwise_add(x, proj)
        b = layers.layer_norm(x, begin_norm_axis=2)
        f = layers.fc(b, d_ff, num_flatten_dims=2, act="gelu",
                      param_attr=ParamAttr(shard_spec=(None, "tp")))
        f = layers.fc(f, d_model, num_flatten_dims=2,
                      param_attr=ParamAttr(shard_spec=("tp", None)))
        if dropout_rate:
            f = layers.dropout(f, dropout_prob=dropout_rate)
        return layers.elementwise_add(x, f)

    def layer_pair(x):
        return encoder_layer(encoder_layer(x))

    # remat two layers per segment: same activation-memory class, half the
    # checkpoint boundaries (each boundary costs layout/staging copies)
    i = 0
    while i < n_layers:
        if remat and i + 1 < n_layers:
            h = layers.recompute(layer_pair, h)
            i += 2
        elif remat:
            h = layers.recompute(encoder_layer, h)
            i += 1
        else:
            h = encoder_layer(h)
            i += 1

    h = layers.layer_norm(h, begin_norm_axis=2)
    loss = _chunked_lm_head(h, labels, vocab_size, seq_len, head_chunk)
    return tokens, labels, loss


def _chunked_lm_head(h, labels, vocab_size, seq_len, head_chunk=None):
    """Vocab projection -> mean CE, chunked along the sequence. No remat
    here: softmax_with_cross_entropy's custom vjp keeps only the (bf16)
    logits as residuals and recomputes the softmax elementwise in
    backward, so the expensive vocab matmul runs exactly once. Chunking
    bounds the fp32 log-softmax TRANSIENT to [B, chunk, vocab]
    (full-sequence fp32 temps peak over a 16G chip's HBM at batch 128).
    The mean divides by the RUNTIME token count (labels' shape) so the -1
    batch dim needs no trace-time value."""
    def lm_head_sum(x, y):
        logits = layers.fc(x, vocab_size, num_flatten_dims=2,
                           bias_attr=False,
                           param_attr=ParamAttr(name="lm_head_w",
                                                shard_spec=(None, "tp")))
        y3 = layers.reshape(y, shape=[0, 0, 1])
        ce = layers.softmax_with_cross_entropy(logits, y3)
        return layers.reduce_sum(ce)

    head_chunk = min(seq_len, head_chunk or 256)
    parts = []
    for s in range(0, seq_len, head_chunk):
        hs = layers.slice(h, axes=[1], starts=[s], ends=[s + head_chunk])
        ys = layers.slice(labels, axes=[1], starts=[s],
                          ends=[s + head_chunk])
        parts.append(lm_head_sum(hs, ys))
    total = parts[0] if len(parts) == 1 else layers.sums(parts)
    numel = layers.cast(layers.reduce_prod(layers.shape(labels)),
                        "float32")
    return layers.elementwise_div(total, numel)


def build_stacked(vocab_size=32000, d_model=512, n_heads=8, n_layers=6,
                  d_ff=2048, seq_len=512, dtype="bfloat16"):
    """The same flagship LM with the layer stack expressed as ONE
    StaticRNN(remat=True) over STACKED per-layer weights — the structure
    the bespoke native model uses (lax.scan over a jax.checkpoint body,
    models/transformer.py single_chip_forward), available through the
    Fluid layers API. One compiled layer body instead of n_layers unrolled
    copies: XLA optimizes a single step and the scan re-runs it, which
    collapses the per-layer boundary/staging overhead of the unrolled
    build(). Weights live as [n_layers, ...] stacked parameters (scanned
    on axis 0 via StaticRNN.step_input)."""
    from ..initializer import Constant, Normal

    tokens = layers.data(name="tokens", shape=[seq_len], dtype="int32")
    labels = layers.data(name="labels", shape=[seq_len], dtype="int32")

    h = layers.embedding(tokens, size=[vocab_size, d_model], dtype=dtype)
    h = layers.scale(h, scale=float(d_model) ** 0.5)
    h = layers.add_position_encoding(h, alpha=1.0, beta=1.0)

    L, D, F = n_layers, d_model, d_ff

    def P(name, shape, init_std=0.02, const=None):
        init = (Constant(const) if const is not None
                else Normal(0.0, init_std))
        return layers.create_parameter(shape=shape, dtype=dtype, name=name,
                                       default_initializer=init)

    wqkv = P("st_wqkv", [L, D, 3 * D])
    bqkv = P("st_bqkv", [L, 3 * D], const=0.0)
    wproj = P("st_wproj", [L, D, D])
    bproj = P("st_bproj", [L, D], const=0.0)
    ln1_s = P("st_ln1_s", [L, D], const=1.0)
    ln1_b = P("st_ln1_b", [L, D], const=0.0)
    ln2_s = P("st_ln2_s", [L, D], const=1.0)
    ln2_b = P("st_ln2_b", [L, D], const=0.0)
    wff1 = P("st_wff1", [L, D, F])
    bff1 = P("st_bff1", [L, F], const=0.0)
    wff2 = P("st_wff2", [L, F, D])
    bff2 = P("st_bff2", [L, D], const=0.0)

    def ln(x, scale, shift):
        # fp32 stats, stream dtype out (layer_norm-kernel semantics, built
        # from primitives because the scanned params come in as step vars)
        xf = layers.cast(x, "float32")
        mu = layers.reduce_mean(xf, dim=-1, keep_dim=True)
        d = layers.elementwise_sub(xf, mu)
        var = layers.reduce_mean(layers.elementwise_mul(d, d), dim=-1,
                                 keep_dim=True)
        inv = layers.rsqrt(layers.scale(var, scale=1.0, bias=1e-5))
        y = layers.cast(layers.elementwise_mul(d, inv), dtype)
        return layers.elementwise_add(
            layers.elementwise_mul(y, scale), shift)

    rnn = layers.StaticRNN(remat=True)
    with rnn.step():
        w1 = rnn.step_input(wqkv)
        b1 = rnn.step_input(bqkv)
        w2 = rnn.step_input(wproj)
        b2 = rnn.step_input(bproj)
        s1 = rnn.step_input(ln1_s)
        c1 = rnn.step_input(ln1_b)
        s2 = rnn.step_input(ln2_s)
        c2 = rnn.step_input(ln2_b)
        w3 = rnn.step_input(wff1)
        b3 = rnn.step_input(bff1)
        w4 = rnn.step_input(wff2)
        b4_ = rnn.step_input(bff2)
        xm = rnn.memory(init=h)
        a = ln(xm, s1, c1)
        qkv = layers.elementwise_add(layers.matmul(a, w1), b1)
        q, k, v = layers.split(qkv, num_or_sections=3, dim=-1)
        att3 = nets.scaled_dot_product_attention(
            q, k, v, num_heads=n_heads, causal=True)
        x = layers.elementwise_add(
            xm, layers.elementwise_add(layers.matmul(att3, w2), b2))
        bnorm = ln(x, s2, c2)
        f = layers.gelu(layers.elementwise_add(layers.matmul(bnorm, w3),
                                               b3))
        x_new = layers.elementwise_add(
            x, layers.elementwise_add(layers.matmul(f, w4), b4_))
        rnn.update_memory(xm, x_new)
    rnn()
    h = rnn.final_memories[0]

    h_f32 = layers.layer_norm(h, begin_norm_axis=2)
    loss = _chunked_lm_head(h_f32, labels, vocab_size, seq_len)
    return tokens, labels, loss
