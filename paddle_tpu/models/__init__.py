"""Model zoo (parity: /root/reference/benchmark/fluid/models/ — mnist,
resnet, vgg, stacked_dynamic_lstm, machine_translation — plus the flagship
TPU-native Transformer and a DeepFM CTR model for the sparse-embedding
configs in BASELINE.md).

Each model module exposes a `build(...)` function that constructs the
network in the current default Program via the `paddle_tpu.layers` DSL and
returns the variables a training loop needs (loss, inputs, predictions).
"""

from . import mnist  # noqa: F401
from . import resnet  # noqa: F401
from . import vgg  # noqa: F401
from . import stacked_lstm  # noqa: F401
from . import word2vec  # noqa: F401
from . import machine_translation  # noqa: F401
from . import deepfm  # noqa: F401
from . import transformer  # noqa: F401
from . import transformer_fluid  # noqa: F401
from . import se_resnext  # noqa: F401
