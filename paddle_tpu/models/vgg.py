"""VGG-16 (parity: benchmark/fluid/models/vgg.py vgg16_bn_drop)."""

from .. import layers, nets


def vgg16_bn_drop(input, class_dim=10, is_test=False):
    def conv_block(ipt, num_filter, groups):
        return nets.img_conv_group(
            input=ipt,
            pool_size=2,
            pool_stride=2,
            conv_num_filter=[num_filter] * groups,
            conv_filter_size=3,
            conv_act="relu",
            conv_with_batchnorm=True,
            pool_type="max")

    conv1 = conv_block(input, 64, 2)
    conv2 = conv_block(conv1, 128, 2)
    conv3 = conv_block(conv2, 256, 3)
    conv4 = conv_block(conv3, 512, 3)
    conv5 = conv_block(conv4, 512, 3)

    drop = layers.dropout(x=conv5, dropout_prob=0.5, is_test=is_test)
    fc1 = layers.fc(input=drop, size=512, act=None)
    bn = layers.batch_norm(input=fc1, act="relu", is_test=is_test)
    drop2 = layers.dropout(x=bn, dropout_prob=0.5, is_test=is_test)
    fc2 = layers.fc(input=drop2, size=512, act=None)
    return layers.fc(input=fc2, size=class_dim, act="softmax")


def build(dataset="cifar10", class_dim=None, is_test=False):
    dshape = [3, 32, 32] if dataset == "cifar10" else [3, 224, 224]
    class_dim = class_dim or (10 if dataset == "cifar10" else 1000)
    img = layers.data(name="img", shape=dshape, dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="int64")
    predict = vgg16_bn_drop(img, class_dim=class_dim, is_test=is_test)
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=predict, label=label)
    return img, label, predict, avg_cost, acc
