"""Interop with the reference's native model artifacts (round-4 VERDICT
missing #2): parse a `__model__` ProgramDesc protobuf
(framework/framework.proto:43-188) plus `save`/`save_combine`-format
LoDTensor parameter files (operators/save_op.cc:25,
framework/lod_tensor.cc:246 SerializeToStream,
framework/tensor_util.cc TensorToStream) into a paddle_tpu Program and
scope values — so a model the reference saved loads and runs here.

The decoder is a minimal proto2 wire reader (varint / fixed64 /
length-delimited / fixed32) driven by field-number tables transcribed
from framework.proto; no protobuf runtime needed. Repeated numeric
fields accept both packed and unpacked encodings.
"""

import os
import struct

import numpy as np

from . import framework

__all__ = ["program_from_reference_bytes", "read_lod_tensor",
           "load_reference_persistables", "is_reference_program_bytes"]


# ---------------------------------------------------------------------------
# proto2 wire reader
# ---------------------------------------------------------------------------


def _varint(buf, pos):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("malformed varint")


def _signed(v):
    """Two's-complement 64-bit interpretation (proto int32/int64 encode
    negatives as 10-byte varints)."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _fields(buf):
    """Yield (field_number, wire_type, raw_value) over a message buffer.
    wire 0 -> unsigned varint int, 1 -> 8 raw bytes, 2 -> bytes,
    5 -> 4 raw bytes."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = _varint(buf, pos)
        elif wire == 1:
            val = buf[pos:pos + 8]
            pos += 8
        elif wire == 2:
            ln, pos = _varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            val = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError("unsupported wire type %d" % wire)
        yield field, wire, val


def _packed_varints(wire, val, out):
    """Append a repeated-varint field occurrence: unpacked (one varint)
    or packed (length-delimited run of varints)."""
    if wire == 0:
        out.append(_signed(val))
    else:
        pos = 0
        while pos < len(val):
            v, pos = _varint(val, pos)
            out.append(_signed(v))


def _f32(val):
    return struct.unpack("<f", val)[0]


# ---------------------------------------------------------------------------
# framework.proto message tables
# ---------------------------------------------------------------------------


def _parse_tensor_desc(buf):
    """VarType.TensorDesc: data_type=1 (enum), dims=2 (repeated int64)."""
    desc = {"data_type": None, "dims": []}
    for field, wire, val in _fields(buf):
        if field == 1:
            desc["data_type"] = val
        elif field == 2:
            _packed_varints(wire, val, desc["dims"])
    return desc


def _parse_lod_tensor_desc(buf):
    """VarType.LoDTensorDesc: tensor=1, lod_level=2."""
    desc = {"tensor": None, "lod_level": 0}
    for field, wire, val in _fields(buf):
        if field == 1:
            desc["tensor"] = _parse_tensor_desc(val)
        elif field == 2:
            desc["lod_level"] = val
    return desc


def _parse_var_type(buf):
    """VarType: type=1 (enum), selected_rows=2, lod_tensor=3,
    tensor_array=4."""
    vt = {"type": None, "tensor": None, "lod_level": 0}
    for field, wire, val in _fields(buf):
        if field == 1:
            vt["type"] = val
        elif field == 2:
            vt["tensor"] = _parse_tensor_desc(val)
        elif field == 3:
            lt = _parse_lod_tensor_desc(val)
            vt["tensor"] = lt["tensor"]
            vt["lod_level"] = lt["lod_level"]
        elif field == 4:
            lt = _parse_lod_tensor_desc(val)
            vt["tensor"] = lt["tensor"]
            vt["lod_level"] = lt["lod_level"]
    return vt


def _parse_var_desc(buf):
    """VarDesc: name=1, type=2, persistable=3."""
    vd = {"name": None, "type": None, "persistable": False}
    for field, wire, val in _fields(buf):
        if field == 1:
            vd["name"] = val.decode("utf-8")
        elif field == 2:
            vd["type"] = _parse_var_type(val)
        elif field == 3:
            vd["persistable"] = bool(val)
    return vd


def _parse_op_var(buf):
    """OpDesc.Var: parameter=1, arguments=2."""
    param, args = None, []
    for field, wire, val in _fields(buf):
        if field == 1:
            param = val.decode("utf-8")
        elif field == 2:
            args.append(val.decode("utf-8"))
    return param, args


# AttrType enum (framework.proto:26): INT FLOAT STRING INTS FLOATS
# STRINGS BOOLEAN BOOLEANS BLOCK LONG BLOCKS LONGS
_A_INT, _A_FLOAT, _A_STRING, _A_INTS, _A_FLOATS, _A_STRINGS, \
    _A_BOOLEAN, _A_BOOLEANS, _A_BLOCK, _A_LONG, _A_BLOCKS, _A_LONGS = \
    range(12)


def _parse_op_attr(buf):
    """OpDesc.Attr: name=1 type=2 i=3 f=4 s=5 ints=6 floats=7 strings=8
    b=10 bools=11 block_idx=12 l=13 blocks_idx=14 longs=15."""
    name, atype = None, None
    scalars = {}
    ints, floats, strings, bools, blocks_idx, longs = [], [], [], [], [], []
    for field, wire, val in _fields(buf):
        if field == 1:
            name = val.decode("utf-8")
        elif field == 2:
            atype = val
        elif field == 3:
            scalars["i"] = _signed(val)
        elif field == 4:
            scalars["f"] = _f32(val)
        elif field == 5:
            scalars["s"] = val.decode("utf-8")
        elif field == 6:
            _packed_varints(wire, val, ints)
        elif field == 7:
            if wire == 5:
                floats.append(_f32(val))
            else:
                floats.extend(
                    struct.unpack("<%df" % (len(val) // 4), val))
        elif field == 8:
            strings.append(val.decode("utf-8"))
        elif field == 10:
            scalars["b"] = bool(val)
        elif field == 11:
            _packed_varints(wire, val, bools)
        elif field == 12:
            scalars["block_idx"] = _signed(val)
        elif field == 13:
            scalars["l"] = _signed(val)
        elif field == 14:
            _packed_varints(wire, val, blocks_idx)
        elif field == 15:
            _packed_varints(wire, val, longs)
    if atype == _A_INT:
        value = int(scalars.get("i", 0))
    elif atype == _A_FLOAT:
        value = float(scalars.get("f", 0.0))
    elif atype == _A_STRING:
        value = scalars.get("s", "")
    elif atype == _A_INTS:
        value = [int(v) for v in ints]
    elif atype == _A_FLOATS:
        value = [float(v) for v in floats]
    elif atype == _A_STRINGS:
        value = strings
    elif atype == _A_BOOLEAN:
        value = bool(scalars.get("b", False))
    elif atype == _A_BOOLEANS:
        value = [bool(v) for v in bools]
    elif atype == _A_LONG:
        value = int(scalars.get("l", 0))
    elif atype == _A_LONGS:
        value = [int(v) for v in longs]
    elif atype in (_A_BLOCK, _A_BLOCKS):
        value = ("__block__", scalars.get("block_idx"), blocks_idx)
    else:
        value = None
    return name, atype, value


def _parse_op_desc(buf):
    """OpDesc: inputs=1, outputs=2, type=3, attrs=4."""
    od = {"type": None, "inputs": {}, "outputs": {}, "attrs": []}
    for field, wire, val in _fields(buf):
        if field == 3:
            od["type"] = val.decode("utf-8")
        elif field in (1, 2):
            param, args = _parse_op_var(val)
            od["inputs" if field == 1 else "outputs"][param] = args
        elif field == 4:
            od["attrs"].append(_parse_op_attr(val))
    return od


def _parse_block_desc(buf):
    """BlockDesc: idx=1, parent_idx=2, vars=3, ops=4."""
    bd = {"idx": 0, "parent_idx": -1, "vars": [], "ops": []}
    for field, wire, val in _fields(buf):
        if field == 1:
            bd["idx"] = _signed(val)
        elif field == 2:
            bd["parent_idx"] = _signed(val)
        elif field == 3:
            bd["vars"].append(_parse_var_desc(val))
        elif field == 4:
            bd["ops"].append(_parse_op_desc(val))
    return bd


def _parse_program_desc(buf):
    """ProgramDesc: blocks=1, version=2."""
    blocks = []
    for field, wire, val in _fields(buf):
        if field == 1:
            blocks.append(_parse_block_desc(val))
    return blocks


# VarType.Type enum values (framework.proto:106) -> numpy dtypes
_DTYPE_OF = {
    0: "bool", 1: "int16", 2: "int32", 3: "int64", 4: "float16",
    5: "float32", 6: "float64", 19: "uint64", 20: "uint8", 21: "int8",
}
_VT_LOD_TENSOR = 7
_VT_SELECTED_ROWS = 8
_VT_FEED_MINIBATCH = 9
_VT_FETCH_LIST = 10
_VT_LOD_TENSOR_ARRAY = 13
# framework var types the importer materializes as tensors
_TENSOR_TYPES = {_VT_LOD_TENSOR: "LOD_TENSOR",
                 _VT_SELECTED_ROWS: "SELECTED_ROWS",
                 _VT_LOD_TENSOR_ARRAY: "LOD_TENSOR_ARRAY"}


def is_reference_program_bytes(raw):
    """Heuristic sniff: reference __model__ files start with the
    ProgramDesc blocks field tag (field 1, wire 2 -> 0x0A)."""
    return bool(raw) and raw[0] == 0x0A


# ops whose reference sub-block wiring this importer knows how to map
# onto the native lowering's attr conventions
_BLOCK_OP_MAPPERS = {}


def _map_while_op(op):
    """Reference while op (while_op.cc:43: inputs X/Condition, outputs
    Out/StepScopes, attr sub_block) -> the native lowering's derived
    attrs (ops/controlflow.py `while`): carry/cond/x name lists, no
    step-scope bookkeeping (XLA carries state functionally), dynamic
    trip count (lax.while_loop — forward-only, which is what an
    inference export needs)."""
    cond_vars = op.inputs.get("Condition", [])
    if len(cond_vars) != 1:
        raise ValueError("reference while op needs exactly one Condition")
    cond_name = cond_vars[0].name
    # the native carry needs an initial value for every Out var, so
    # write-before-read loop vars join X (the reference lists only reads
    # there; a var present in both stays deduped)
    x_vars = {v.name: v for v in op.inputs.get("X", [])}
    for v in op.outputs.get("Out", []):
        x_vars.setdefault(v.name, v)
    x_vars.pop(cond_name, None)
    x_names = list(x_vars)
    out_names = [v.name for v in op.outputs.get("Out", [])
                 if v.name != cond_name]
    op.inputs = {"Condition": cond_vars, "X": list(x_vars.values())}
    op.outputs = {"Out": [v for v in op.outputs.get("Out", [])
                          if v.name != cond_name]}
    carry = list(out_names)
    if cond_name not in carry:
        carry.append(cond_name)
    op.attrs.update({
        "x_names": x_names, "out_names": out_names,
        "carry_names": carry, "cond_name": cond_name,
        "max_trip_count": op.attrs.get("max_trip_count"),
    })


_BLOCK_OP_MAPPERS["while"] = _map_while_op


def program_from_reference_bytes(raw):
    """ProgramDesc protobuf bytes -> (Program, feed_names, fetch_names).

    `feed`/`fetch` ops (appended by the reference's save_inference_model,
    io.py:880-897) are stripped into the returned name lists, keyed by
    their `col` attr; the FEED_MINIBATCH / FETCH_LIST holder vars are
    dropped. Multi-block programs import when every block-carrying op
    has a registered mapper (`while`); others reject loudly."""
    blocks = _parse_program_desc(raw)
    if not blocks:
        raise ValueError("no blocks in ProgramDesc")
    p = framework.Program()
    p.blocks = []
    for bd in blocks:
        blk = framework.Block(p, bd["idx"], bd["parent_idx"])
        p.blocks.append(blk)

    for bd, blk in zip(blocks, p.blocks):
        for vd in bd["vars"]:
            vt = vd["type"] or {}
            if vt.get("type") not in _TENSOR_TYPES:
                continue  # feed/fetch holders, scopes, readers, raw
            tensor = vt.get("tensor") or {}
            dims = tensor.get("dims") or None
            dtype = _DTYPE_OF.get(tensor.get("data_type"), "float32")
            v = framework.Variable(
                blk, name=vd["name"],
                shape=tuple(dims) if dims is not None else None,
                dtype=dtype, lod_level=int(vt.get("lod_level", 0)),
                persistable=vd["persistable"],
                type=_TENSOR_TYPES[vt["type"]])
            blk.vars[v.name] = v

    feeds, fetches = {}, {}
    block_ops = []  # ops needing post-construction attr mapping
    for bd, blk in zip(blocks, p.blocks):
        for od in bd["ops"]:
            attrs = {}
            has_block_attr = False
            for name, atype, value in od["attrs"]:
                if atype == _A_BLOCK:
                    has_block_attr = True
                    attrs[name] = p.blocks[value[1]]
                elif atype == _A_BLOCKS:
                    has_block_attr = True
                    attrs[name] = [p.blocks[i] for i in value[2]]
                elif name in ("dtype", "out_dtype", "in_dtype") \
                        and isinstance(value, int):
                    # the reference stores dtype attrs as VarType enum
                    # ints (framework.proto:106); the native ops take
                    # numpy dtype names
                    attrs[name] = _DTYPE_OF.get(value, "float32")
                else:
                    attrs[name] = value
            if has_block_attr and od["type"] not in _BLOCK_OP_MAPPERS:
                raise NotImplementedError(
                    "reference op %r carries a sub-block attr — only %s "
                    "import; rebuild other control flow with "
                    "paddle_tpu.layers" % (
                        od["type"], sorted(_BLOCK_OP_MAPPERS)))
            if od["type"] == "feed":
                for arg in od["outputs"].get("Out", []):
                    feeds[int(attrs.get("col", len(feeds)))] = arg
                continue
            if od["type"] == "fetch":
                for arg in od["inputs"].get("X", []):
                    fetches[int(attrs.get("col", len(fetches)))] = arg
                continue

            def _vars(names, _blk=blk):
                out = []
                for n in names:
                    v = _blk._find_var_recursive(n)
                    if v is None:
                        # reference programs may reference vars declared
                        # with no tensor desc; materialize shapeless
                        v = framework.Variable(_blk, name=n, shape=None)
                        _blk.vars[n] = v
                    out.append(v)
                return out

            # step-scope bookkeeping outputs have no tensor meaning here
            outs = {k: ns for k, ns in od["outputs"].items()
                    if k not in ("StepScopes", "Scope")}
            op = blk.append_op(
                type=od["type"],
                inputs={k: _vars(ns) for k, ns in od["inputs"].items()},
                outputs={k: _vars(ns) for k, ns in outs.items()},
                attrs=attrs)
            if has_block_attr:
                block_ops.append(op)
    for op in block_ops:
        _BLOCK_OP_MAPPERS[op.type](op)
    p.current_block_idx = 0
    feed_names = [feeds[k] for k in sorted(feeds)]
    fetch_names = [fetches[k] for k in sorted(fetches)]
    # data vars: the feed targets (reference marks them only via feed ops)
    for n in feed_names:
        v = p.global_block()._find_var_recursive(n)
        if v is not None:
            v.is_data = True
    return p, feed_names, fetch_names


# ---------------------------------------------------------------------------
# LoDTensor streams (save_op.cc:25 / lod_tensor.cc:246 / tensor_util.cc)
# ---------------------------------------------------------------------------


def read_lod_tensor(f):
    """One serialized LoDTensor from a binary stream:
    uint32 version(0) | uint64 lod_level_count | per level: uint64 nbytes
    + size_t offsets | uint32 tensor version(0) | int32 desc_size |
    TensorDesc proto | raw data."""
    version = struct.unpack("<I", f.read(4))[0]
    if version != 0:
        raise ValueError("unsupported LoDTensor version %d" % version)
    (lod_levels,) = struct.unpack("<Q", f.read(8))
    lod = []
    for _ in range(lod_levels):
        (nbytes,) = struct.unpack("<Q", f.read(8))
        data = f.read(nbytes)
        lod.append(list(struct.unpack("<%dQ" % (nbytes // 8), data)))
    version = struct.unpack("<I", f.read(4))[0]
    if version != 0:
        raise ValueError("unsupported tensor version %d" % version)
    (desc_size,) = struct.unpack("<i", f.read(4))
    desc = _parse_tensor_desc(f.read(desc_size))
    dtype = np.dtype(_DTYPE_OF[desc["data_type"]])
    dims = [int(d) for d in desc["dims"]]
    count = int(np.prod(dims)) if dims else 1
    arr = np.frombuffer(f.read(count * dtype.itemsize), dtype=dtype)
    arr = arr.reshape(dims).copy()
    return arr, lod


def load_reference_persistables(dirname, program, filename=None,
                                scope=None):
    """Populate the scope with the program's persistable vars from
    reference-format files: one combined file (save_combine — streams
    concatenated in SORTED name order, io.py:625) or per-var files named
    by variable (save_op)."""
    from .core.scope import global_scope

    scope = scope if scope is not None else global_scope()
    names = sorted(v.name for v in program.list_vars() if v.persistable)
    if filename is not None:
        with open(os.path.join(dirname, filename), "rb") as f:
            for name in names:
                arr, _ = read_lod_tensor(f)
                scope.set(name, arr)
            if f.read(1):
                raise ValueError(
                    "trailing bytes in %s after %d tensors — the file "
                    "holds more vars than the program's persistables"
                    % (filename, len(names)))
    else:
        for name in names:
            with open(os.path.join(dirname, name), "rb") as f:
                arr, _ = read_lod_tensor(f)
            scope.set(name, arr)
    return names
