"""DataFeeder (parity: python/paddle/fluid/data_feeder.py) — converts a
batch of python rows into the executor feed dict."""

import numpy as np

from .core.tensor import LoDTensor
from .framework import Variable, default_main_program, dtype_to_np

__all__ = ["DataFeeder"]


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None):
        self.feed_names = []
        self.feed_shapes = []
        self.feed_dtypes = []
        self.feed_lod_level = []
        program = program or default_main_program()
        for v in feed_list:
            if isinstance(v, str):
                v = program.global_block().var(v)
            self.feed_names.append(v.name)
            self.feed_shapes.append(v.shape)
            self.feed_dtypes.append(dtype_to_np(v.dtype))
            self.feed_lod_level.append(v.lod_level)
        self.place = place

    def feed(self, iterable):
        """iterable: list of rows, each row a tuple matching feed_list."""
        columns = [[] for _ in self.feed_names]
        for row in iterable:
            for i, cell in enumerate(row):
                columns[i].append(np.asarray(cell))
        out = {}
        for name, col, shape, dt, lod in zip(
            self.feed_names, columns, self.feed_shapes, self.feed_dtypes,
            self.feed_lod_level,
        ):
            if lod > 0:
                # ragged: pad to max length; lod kept on a LoDTensor wrapper
                maxlen = max(c.shape[0] for c in col)
                padded = np.zeros((len(col), maxlen) + col[0].shape[1:], dt)
                lengths = []
                for i, c in enumerate(col):
                    padded[i, : c.shape[0]] = c
                    lengths.append(c.shape[0])
                t = LoDTensor(padded)
                t.set_recursive_sequence_lengths([lengths])
                out[name] = padded.astype(dt)
            else:
                arr = np.stack(col).astype(dt)
                # honor declared trailing shape (e.g. [-1, 1] labels)
                if shape is not None:
                    want_rank = len(shape)
                    while arr.ndim < want_rank:
                        arr = arr[..., None]
                    if arr.ndim == want_rank:
                        tgt = [d if d != -1 else arr.shape[i]
                               for i, d in enumerate(shape)]
                        if int(np.prod(tgt)) == arr.size:
                            arr = arr.reshape(tgt)
                out[name] = arr
        return out

    def decorate_reader(self, reader, multi_devices, num_places=None,
                        drop_last=True):
        """Wrap a sample-batch reader into one yielding converted feed
        dicts (parity: data_feeder.py:368 decorate_reader). With
        multi_devices, consecutive mini-batches group per device — the
        data-parallel executor concatenates them into one sharded feed."""
        def _reader():
            if not multi_devices:
                for batch in reader():
                    yield self.feed(batch)
                return
            n = num_places or 1
            group = []
            for batch in reader():
                group.append(self.feed(batch))
                if len(group) == n:
                    yield group
                    group = []
            if group and not drop_last:
                raise ValueError(
                    "trailing %d mini-batch(es) do not fill all %d "
                    "devices; pass drop_last=True" % (len(group), n))
        return _reader

    def feed_parallel(self, iterable, num_places=None):
        """One mini-batch per device, fed in advance (parity:
        data_feeder.py:292 feed_parallel). Yields one converted feed dict
        per place; the data-parallel executor splits its global batch over
        the mesh, so equal-size per-place batches concatenate to one
        sharded feed."""
        batches = list(iterable)
        if num_places is not None and len(batches) != num_places:
            raise ValueError(
                "feed_parallel needs as many mini-batches as places "
                "(got %d batches for %d places)"
                % (len(batches), num_places))
        return (self.feed(b) for b in batches)


class DataFeedDesc:
    """Declarative feed description (parity: fluid/data_feed_desc.py wrapping
    framework/data_feed.proto). Configures slot names/types/dense-ness for
    Dataset-driven training (train_from_dataset)."""

    def __init__(self, proto_file=None):
        self.name = "MultiSlotDataFeed"
        self.batch_size = 1
        self._batch_size_set = False
        self.slots = []  # dicts: name, type, shape, is_dense, is_used
        self._slot_index = {}
        if proto_file is not None:
            self._parse(proto_file)

    def _parse(self, proto_file):
        import re
        with open(proto_file) as f:
            text = f.read()
        for m in re.finditer(
                r"slots\s*\{([^}]*)\}", text):
            body = m.group(1)
            get = lambda k, d=None: (re.search(k + r':\s*"?([\w.]+)"?', body)
                                     or [None, d])[1]
            self.add_slot(get("name", ""), get("type", "float"),
                          is_dense=get("is_dense", "false") == "true")
        bs = re.search(r"batch_size:\s*(\d+)", text)
        if bs:
            self.batch_size = int(bs.group(1))
            self._batch_size_set = True

    def add_slot(self, name, dtype="float", shape=None, is_dense=False,
                 pad_value=0):
        self._slot_index[name] = len(self.slots)
        self.slots.append({"name": name, "type": dtype,
                           "shape": list(shape or []),
                           "is_dense": is_dense, "is_used": True,
                           "pad_value": pad_value})
        return self

    def set_batch_size(self, batch_size):
        self.batch_size = batch_size
        self._batch_size_set = True

    def set_dense_slots(self, dense_slots_name):
        for n in dense_slots_name:
            self.slots[self._slot_index[n]]["is_dense"] = True

    def set_hash_mod(self, hash_mods):
        """Per-slot host-side id folding, `{slot_name: mod}`. Raw uint64
        feature hashes are reduced `id % mod` on the HOST while parsing —
        the device graph never carries 64-bit ids (JAX canonicalizes
        int64 device arrays to int32, which would silently truncate ids
        above 2^31). `mod` is normally the embedding table's num_rows."""
        for n, v in hash_mods.items():
            self.slots[self._slot_index[n]]["hash_mod"] = int(v)

    def set_pad_value(self, pad_values):
        """Per-slot batch pad value, `{slot_name: value}`. Ragged id slots
        batch padded-dense; padding with the embedding's declared
        padding_idx keeps pad rows out of sum-pooled lookups (the
        reference's LoD batching has no pad contributions at all)."""
        for n, v in pad_values.items():
            self.slots[self._slot_index[n]]["pad_value"] = v

    def set_use_slots(self, use_slots_name):
        for s in self.slots:
            s["is_used"] = False
        for n in use_slots_name:
            self.slots[self._slot_index[n]]["is_used"] = True

    def desc(self):
        lines = ["name: \"%s\"" % self.name,
                 "batch_size: %d" % self.batch_size]
        for s in self.slots:
            lines.append(
                "slots {\n  name: \"%s\"\n  type: \"%s\"\n  is_dense: %s\n"
                "  is_used: %s\n}" % (s["name"], s["type"],
                                      str(s["is_dense"]).lower(),
                                      str(s["is_used"]).lower()))
        return "\n".join(lines)
