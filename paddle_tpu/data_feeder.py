"""DataFeeder (parity: python/paddle/fluid/data_feeder.py) — converts a
batch of python rows into the executor feed dict."""

import numpy as np

from .core.tensor import LoDTensor
from .framework import Variable, default_main_program, dtype_to_np

__all__ = ["DataFeeder"]


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None):
        self.feed_names = []
        self.feed_shapes = []
        self.feed_dtypes = []
        self.feed_lod_level = []
        program = program or default_main_program()
        for v in feed_list:
            if isinstance(v, str):
                v = program.global_block().var(v)
            self.feed_names.append(v.name)
            self.feed_shapes.append(v.shape)
            self.feed_dtypes.append(dtype_to_np(v.dtype))
            self.feed_lod_level.append(v.lod_level)
        self.place = place

    def feed(self, iterable):
        """iterable: list of rows, each row a tuple matching feed_list."""
        columns = [[] for _ in self.feed_names]
        for row in iterable:
            for i, cell in enumerate(row):
                columns[i].append(np.asarray(cell))
        out = {}
        for name, col, shape, dt, lod in zip(
            self.feed_names, columns, self.feed_shapes, self.feed_dtypes,
            self.feed_lod_level,
        ):
            if lod > 0:
                # ragged: pad to max length; lod kept on a LoDTensor wrapper
                maxlen = max(c.shape[0] for c in col)
                padded = np.zeros((len(col), maxlen) + col[0].shape[1:], dt)
                lengths = []
                for i, c in enumerate(col):
                    padded[i, : c.shape[0]] = c
                    lengths.append(c.shape[0])
                t = LoDTensor(padded)
                t.set_recursive_sequence_lengths([lengths])
                out[name] = padded.astype(dt)
            else:
                arr = np.stack(col).astype(dt)
                # honor declared trailing shape (e.g. [-1, 1] labels)
                if shape is not None:
                    want_rank = len(shape)
                    while arr.ndim < want_rank:
                        arr = arr[..., None]
                    if arr.ndim == want_rank:
                        tgt = [d if d != -1 else arr.shape[i]
                               for i, d in enumerate(shape)]
                        if int(np.prod(tgt)) == arr.size:
                            arr = arr.reshape(tgt)
                out[name] = arr
        return out
