"""Automatic mixed-precision (bf16) training (parity:
fluid.contrib.mixed_precision.decorate — decorator.py:26
OptimizerWithMixedPrecision; recipe: Micikevicius et al., *Mixed Precision
Training*, ICLR 2018 + Megatron-LM DDP gradient bucketing).

TPU-native, AMP is a COMPILE-TIME dtype rewrite, not a per-op kernel
switch: the `amp_rewrite` pass (registered in `fluid.ir`'s registry and
run by the default PR-3 pipeline right before constant_fold/cse) walks
the op graph and casts the inputs of matmul/conv/attention-class ops to
bfloat16 — the MXU's native input type — while blacklisted ops
(softmax/log/exp/norm/reduce/loss) and every persistable parameter stay
fp32. Because gradient ops re-run their forward op's kernel under
`jax.vjp` (core/lowering.py), the backward follows the forward's dtypes
automatically: a bf16 forward dot yields bf16 gradient dots and bf16
parameter gradients — half the HBM traffic and half the collective bytes
on a data-parallel mesh — with ZERO grad-op rewriting.

Master weights: fp32-stored params are their own master copy — the pass
inserts `cast(param) -> bf16` ops feeding the white-list consumers, so
the bf16 compute copy is re-derived inside the SAME fused jitted step
(no extra buffers, no device syncs) while the optimizer update applies
to the fp32 original (optimizer kernels cast the incoming bf16 gradient
to fp32 exactly once — ops/optimizer_ops.py). For bf16/f16-STORED params
(e.g. a model built with dtype="bfloat16"), `decorate(...)` creates an
explicit fp32 master Parameter per low-precision param: the startup
program initializes it from the param, the optimizer update runs on the
master, and one trailing in-step cast re-derives the low-precision copy.

Loss scaling rides behind a knob: OFF by default for bfloat16 (same
exponent range as fp32) and ON by default for float16, using the same
check_finite_and_unscale / update_loss_scaling state machine as the
contrib decorator (ops/quant_ops.py).

Activation: `decorate(...)` marks the program (`program._amp_config`);
`PTPU_AMP=1` activates a default config process-wide (level
`PTPU_AMP_LEVEL`, dtype `PTPU_AMP_DTYPE`); `BuildStrategy.amp = True`
activates it for one CompiledProgram. With all three unset, the pass
pipeline, the compile-cache keys and every lowered program are BITWISE
identical to the pre-AMP framework (pinned by tests/test_amp.py).

Gradient bucketing: `plan_buckets` coalesces per-parameter gradients
into flattened same-dtype buckets (size `PTPU_AMP_BUCKET_MB`, default
4 MiB) so data-parallel reduce-scatter/all-reduce moves a few large
low-precision collectives instead of many small fp32 ones — consumed by
`parallel.ShardedAdam(bucket_mb=...)` (docs/MIXED_PRECISION.md).
"""

import hashlib

import numpy as np

from . import framework, unique_name
from .flags import env as _env
from .framework import convert_dtype, default_startup_program
from .ir import Pass, register_pass
from .observability import metrics as _metrics

__all__ = [
    "AutoMixedPrecisionLists", "AmpConfig", "AmpOptimizer", "decorate",
    "amp_env_enabled", "active_config", "bucket_bytes_from_env",
    "mb_to_bucket_bytes", "plan_buckets", "flatten_bucket",
    "unflatten_bucket",
]

# white list: MXU-class ops whose fp32 inputs are cast to the low
# precision dtype (their outputs then carry it)
DEFAULT_WHITE_OPS = frozenset({
    "mul", "matmul", "conv2d", "conv3d", "depthwise_conv2d",
    "conv2d_transpose", "conv2d_fusion", "flash_attention",
    "fused_multihead_attention",
})

# black list: numerically sensitive ops pinned to fp32 — low-precision
# values reaching them are cast back up first
DEFAULT_BLACK_OPS = frozenset({
    "softmax", "softmax_with_cross_entropy", "cross_entropy",
    "cross_entropy2", "sigmoid_cross_entropy_with_logits",
    "square_error_cost", "huber_loss", "smooth_l1", "log_loss",
    "mean", "sum", "reduce_sum", "reduce_mean", "reduce_prod",
    "reduce_max", "reduce_min",
    "layer_norm", "batch_norm", "group_norm", "instance_norm",
    "exp", "log", "rsqrt", "sqrt", "pow", "softmax_with_upper_triangular",
})

_DEFAULT_BUCKET_MB = 4.0


class AutoMixedPrecisionLists:
    """White list computes in the low-precision dtype, black list stays
    fp32, everything else (gray) follows its inputs (parity:
    contrib/mixed_precision/fp16_lists.py)."""

    def __init__(self, custom_white_list=None, custom_black_list=None):
        self.white_list = set(DEFAULT_WHITE_OPS) | set(custom_white_list
                                                       or ())
        self.black_list = set(DEFAULT_BLACK_OPS) | set(custom_black_list
                                                       or ())
        self.white_list -= self.black_list


class AmpConfig:
    """Resolved AMP policy consumed by the `amp_rewrite` pass.

    level O1: only white-list ops compute low-precision; their outputs
    are cast back to fp32 before any non-white consumer. level O2: low
    precision also flows through gray ops (elementwise/reshape/...) and
    is only raised back to fp32 at black-list / structural seams."""

    def __init__(self, level="O1", dtype="bfloat16", lists=None):
        level = str(level).upper()
        if level not in ("O1", "O2"):
            raise ValueError("amp_level must be 'O1' or 'O2', got %r"
                             % (level,))
        dtype = convert_dtype(dtype)
        if dtype not in ("bfloat16", "float16"):
            raise ValueError(
                "AMP compute dtype must be bfloat16 or float16, got %r"
                % (dtype,))
        self.level = level
        self.dtype = dtype
        self.lists = lists or AutoMixedPrecisionLists()

    def cache_key(self):
        """Short stable digest for the compile-cache pipeline key."""
        h = hashlib.sha1()
        h.update(repr((self.level, self.dtype,
                       sorted(self.lists.white_list),
                       sorted(self.lists.black_list))).encode())
        return "%s:%s:%s" % (self.level, self.dtype, h.hexdigest()[:8])


def amp_env_enabled():
    return bool(_env("PTPU_AMP"))


def _env_config():
    return AmpConfig(level=_env("PTPU_AMP_LEVEL"),
                     dtype=_env("PTPU_AMP_DTYPE"))


def active_config(program=None, build_strategy=None):
    """The AMP config in effect for one compile, or None. Precedence:
    program decoration (amp.decorate) > BuildStrategy.amp >
    PTPU_AMP=1."""
    cfg = getattr(program, "_amp_config", None) if program is not None \
        else None
    if cfg is not None:
        return cfg
    if build_strategy is not None and getattr(build_strategy, "amp",
                                              False):
        return AmpConfig(
            level=getattr(build_strategy, "amp_level", "O1") or "O1",
            dtype=getattr(build_strategy, "amp_dtype", "bfloat16")
            or "bfloat16")
    if amp_env_enabled():
        return _env_config()
    return None


# ---------------------------------------------------------------------------
# the dtype-rewrite pass
# ---------------------------------------------------------------------------


@register_pass("amp_rewrite")
class AmpRewritePass(Pass):
    """Insert low-precision casts around white-list ops on the compile
    clone. Soundness:

      - only forward ops are rewritten; grad ops (``__fwd_op__``),
        optimizer ops and AMP state ops are skipped — the backward
        follows the forward's dtypes through jax.vjp, and the grad-var
        NAME wiring (__grad_in_map__/__grad_out_map__) is positional per
        slot, so rewiring a forward op's input list never breaks it;
      - an op's outputs are only marked low-precision when every float
        output is unfetched, not read/written by sub-blocks, singly
        written and not persistable — fetches, checkpoints and scope
        state keep their pre-AMP dtypes bitwise;
      - parameters are never rewritten in place: the inserted
        ``cast(param)`` is the bf16 compute copy, re-derived inside the
        same jitted step, while the fp32 original stays the master the
        optimizer updates;
      - inserted casts are deduped per (source, reaching definition) and
        any survivors are swept by the pipeline's cse pass behind this
        one.
    """

    def apply(self, program, scope=None):
        cfg = active_config(program)
        if cfg is None:
            return program
        from .core.lowering import _SPECIAL, _STRUCTURAL
        from .framework import (_AMP_STATE_OP_TYPES, _OPTIMIZER_OP_TYPES,
                                Block, Operator)
        from .ir_passes import (_fetch_targets, _outside_reads,
                                _outside_writes, _write_indices)

        targets = _fetch_targets(program)
        if targets is None:
            # fetch set unknown (standalone apply): rewriting could hand
            # a fetched name a low-precision value — pin
            # program._opt_fetch_targets to run this pass standalone
            return program
        block = program.global_block()
        lp = cfg.dtype
        white = cfg.lists.white_list
        black = cfg.lists.black_list
        protected = (set(targets) | _outside_reads(program)
                     | _outside_writes(program))
        writes = _write_indices(block)

        def rdef(name, i):
            last = -1
            for w in writes.get(name, ()):
                if w < i:
                    last = w
                else:
                    break
            return last

        lp_names = set()   # names whose RUNTIME value is low precision
        cast_cache = {}    # (src name, reaching def, dtype) -> Variable
        new_ops = []
        inserted = [0]
        deduped = [0]
        rewritten = 0

        def cast_to(v, i, dtype):
            key = (v.name, rdef(v.name, i), dtype)
            hit = cast_cache.get(key)
            if hit is not None:
                deduped[0] += 1
                return hit
            cv = block.create_var(
                name=unique_name.generate(v.name + "@amp." + dtype),
                shape=v.shape, dtype=dtype, persistable=False)
            new_ops.append(Operator(
                block, "cast", inputs={"X": [v]}, outputs={"Out": [cv]},
                attrs={"in_dtype": v.dtype, "out_dtype": dtype,
                       "__amp_cast__": True}))
            cast_cache[key] = cv
            inserted[0] += 1
            return cv

        def runtime_lp(v):
            return v.name in lp_names or convert_dtype(v.dtype) == lp

        def float_vars(vs_map):
            return [v for vs in vs_map.values() for v in vs
                    if convert_dtype(v.dtype) in ("float32", lp)]

        def out_markable(n):
            v = block._find_var_recursive(n)
            return (n not in protected and len(writes.get(n, ())) == 1
                    and v is not None and not v.persistable
                    and not v.is_data
                    and convert_dtype(v.dtype) in ("float32", lp))

        def force_fp32_inputs(op, i):
            for slot, vs in op.inputs.items():
                op.inputs[slot] = [
                    cast_to(v, i, "float32") if v.name in lp_names else v
                    for v in vs]

        def skip(op):
            return ("__fwd_op__" in op.attrs
                    or op.type in _OPTIMIZER_OP_TYPES
                    or op.type in _AMP_STATE_OP_TYPES
                    or op.attrs.get("__amp_state__")
                    or op.attrs.get("__amp_cast__"))

        def structural(op):
            return (op.type in _STRUCTURAL or op.type in _SPECIAL
                    or any(isinstance(a, (Block, Operator))
                           for a in op.attrs.values()))

        for i, op in enumerate(block.ops):
            if skip(op):
                new_ops.append(op)
                continue
            fouts = [n for n in op.output_names()
                     if convert_dtype(
                         getattr(block._find_var_recursive(n), "dtype",
                                 "int32")) in ("float32", lp)]
            if op.type in white and all(out_markable(n) for n in fouts) \
                    and fouts:
                touched = False
                for slot, vs in op.inputs.items():
                    nvs = []
                    for v in vs:
                        if runtime_lp(v):
                            nvs.append(v)
                            touched = True
                        elif convert_dtype(v.dtype) == "float32":
                            nvs.append(cast_to(v, i, lp))
                            touched = True
                        else:
                            nvs.append(v)
                    op.inputs[slot] = nvs
                if touched:
                    rewritten += 1
                    for n in fouts:
                        lp_names.add(n)
                        block._find_var_recursive(n).dtype = lp
                new_ops.append(op)
                continue
            if op.type in black or structural(op):
                force_fp32_inputs(op, i)
                for n in op.output_names():
                    lp_names.discard(n)
                new_ops.append(op)
                continue
            # gray op
            if cfg.level == "O1":
                # low precision never leaks past the white op itself
                force_fp32_inputs(op, i)
                for n in op.output_names():
                    lp_names.discard(n)
            else:
                fins = float_vars(op.inputs)
                if fins and any(runtime_lp(v) for v in fins) \
                        and not all(out_markable(n) for n in fouts):
                    # a protected/rebound output must keep fp32: raise
                    # the inputs back up instead of tracking the name
                    force_fp32_inputs(op, i)
                    for n in op.output_names():
                        lp_names.discard(n)
                elif op.type == "cast":
                    od = convert_dtype(op.attrs.get("out_dtype",
                                                    "float32"))
                    for n in op.output_names():
                        (lp_names.add if od == lp
                         else lp_names.discard)(n)
                elif fins and all(runtime_lp(v) for v in fins):
                    for n in fouts:
                        lp_names.add(n)
                        block._find_var_recursive(n).dtype = lp
                else:
                    for n in op.output_names():
                        lp_names.discard(n)
            new_ops.append(op)

        if not inserted[0] and not rewritten:
            # nothing marked AND nothing cast — truly untouched (a
            # bf16-built model can rewrite white ops without inserting
            # a single cast; it must still version-bump and report)
            return program
        block.ops = new_ops
        if inserted[0]:
            _metrics.counter("amp/casts_inserted").inc(inserted[0])
        if deduped[0]:
            _metrics.counter("amp/casts_deduped").inc(deduped[0])
        _metrics.counter("amp/ops_rewritten").inc(rewritten)
        program._bump_version()
        return program


# ---------------------------------------------------------------------------
# optimizer decoration: master weights + loss scaling
# ---------------------------------------------------------------------------


class AmpOptimizer:
    """`decorate(...)` result (parity: OptimizerWithMixedPrecision).
    Marks the program for the `amp_rewrite` pass, optionally scales the
    loss with the dynamic loss-scaling state machine, and maintains fp32
    master weights for low-precision-stored parameters."""

    def __init__(self, optimizer, config, init_loss_scaling,
                 use_dynamic_loss_scaling, incr_every_n_steps,
                 decr_every_n_nan_or_inf, incr_ratio, decr_ratio,
                 master_weight=True):
        self._optimizer = optimizer
        self._config = config
        self._init_loss_scaling = float(init_loss_scaling)
        self._use_dynamic = bool(use_dynamic_loss_scaling)
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._master_weight = master_weight
        self._loss_scaling = None
        self._overflow_steps = None
        self._masters = {}  # param name -> master Parameter

    # -- parity surface ----------------------------------------------------
    def get_loss_scaling(self):
        return self._loss_scaling

    def get_scaled_loss(self):
        return getattr(self, "_scaled_loss", None)

    def _scaling_on(self):
        return self._use_dynamic or self._init_loss_scaling != 1.0

    # -- graph construction ------------------------------------------------
    def _mk_state(self, prog, startup, name, value, dtype="float32"):
        from .initializer import Constant

        vname = unique_name.generate(name)
        gb = prog.global_block()
        v = gb.create_var(name=vname, shape=(1,), dtype=dtype,
                          persistable=True, stop_gradient=True)
        sb = startup.global_block()
        sv = sb.create_var(name=vname, shape=(1,), dtype=dtype,
                           persistable=True)
        Constant(value)(sv, sb)
        return v

    def _create_scaling_state(self, prog, startup):
        self._loss_scaling = self._mk_state(prog, startup, "loss_scaling",
                                            self._init_loss_scaling)
        self._good_steps = self._mk_state(prog, startup, "amp_good_steps",
                                          0, "int32")
        self._bad_steps = self._mk_state(prog, startup, "amp_bad_steps",
                                         0, "int32")
        self._overflow_steps = self._mk_state(
            prog, startup, "amp_overflow_steps", 0, "int32")

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        prog = loss.block.program
        prog._amp_config = self._config
        startup = startup_program or default_startup_program()
        self._startup_program = startup
        if self._scaling_on():
            self._create_scaling_state(prog, startup)
            with framework.program_guard(prog, startup):
                from .layers import nn as nn_layers

                loss = nn_layers.elementwise_mul(loss, self._loss_scaling)
        self._scaled_loss = loss
        return self._optimizer.backward(loss, startup_program,
                                        parameter_list, no_grad_set,
                                        callbacks)

    def _unscale(self, prog, params_grads):
        """check_finite_and_unscale (+ dynamic update + cumulative
        overflow counter) — contrib decorator parity, ops pruned from
        for_test clones via _AMP_STATE_OP_TYPES / __amp_state__."""
        block = prog.global_block()
        grads = [g for _, g in params_grads]
        found_inf = block.create_var(
            name=unique_name.generate("amp_found_inf"), dtype="bool",
            shape=(1,))
        unscaled = []
        for _, g in params_grads:
            ng = block.create_var(name=g.name + "@UNSCALED", dtype=g.dtype,
                                  shape=g.shape)
            unscaled.append(ng)
        block.append_op(
            type="check_finite_and_unscale",
            inputs={"X": grads, "Scale": [self._loss_scaling]},
            outputs={"Out": unscaled, "FoundInfinite": [found_inf]})
        if self._use_dynamic:
            block.append_op(
                type="update_loss_scaling",
                inputs={"PrevLossScaling": [self._loss_scaling],
                        "InGoodSteps": [self._good_steps],
                        "InBadSteps": [self._bad_steps],
                        "FoundInfinite": [found_inf]},
                outputs={"LossScaling": [self._loss_scaling],
                         "OutGoodSteps": [self._good_steps],
                         "OutBadSteps": [self._bad_steps]},
                attrs={"incr_every_n_steps": self._incr_every_n_steps,
                       "decr_every_n_nan_or_inf":
                           self._decr_every_n_nan_or_inf,
                       "incr_ratio": self._incr_ratio,
                       "decr_ratio": self._decr_ratio})
        inc = block.create_var(name=unique_name.generate("amp_ovf_inc"),
                               dtype="int32", shape=(1,))
        block.append_op(type="cast", inputs={"X": [found_inf]},
                        outputs={"Out": [inc]},
                        attrs={"in_dtype": "bool", "out_dtype": "int32",
                               "__amp_state__": True})
        block.append_op(type="elementwise_add",
                        inputs={"X": [self._overflow_steps], "Y": [inc]},
                        outputs={"Out": [self._overflow_steps]},
                        attrs={"__amp_state__": True})
        return [(p, ug) for (p, _), ug in zip(params_grads, unscaled)]

    def _master_for(self, prog, p):
        """fp32 master Parameter for a low-precision-stored param,
        initialized from the param by a cast appended to the startup
        program (decorate before running startup)."""
        m = self._masters.get(p.name)
        if m is not None:
            return m
        gb = prog.global_block()
        m = gb.create_parameter(shape=tuple(p.shape), dtype="float32",
                                name=p.name + ".master", trainable=False)
        m.optimize_attr = dict(p.optimize_attr or {"learning_rate": 1.0})
        m.regularizer = None
        # the startup program backward() resolved (honors an explicit
        # minimize(..., startup_program=...)); default only when the
        # user drove apply_gradients without backward()
        startup = getattr(self, "_startup_program", None) \
            or default_startup_program()
        sb = startup.global_block()
        if sb.has_var(p.name):
            sv = sb.create_var(name=m.name, shape=tuple(p.shape),
                               dtype="float32", persistable=True)
            sb.append_op(type="cast", inputs={"X": [sb.var(p.name)]},
                         outputs={"Out": [sv]},
                         attrs={"in_dtype": p.dtype,
                                "out_dtype": "float32"})
        self._masters[p.name] = m
        return m

    def apply_gradients(self, params_grads):
        if not params_grads:
            return self._optimizer.apply_gradients(params_grads)
        prog = params_grads[0][0].block.program
        block = prog.global_block()
        if self._scaling_on():
            with framework.program_guard(prog):
                params_grads = self._unscale(prog, params_grads)
        low_prec = []
        routed = []
        for p, g in params_grads:
            if self._master_weight and convert_dtype(p.dtype) in (
                    "bfloat16", "float16"):
                master = self._master_for(prog, p)
                low_prec.append((p, master))
                routed.append((master, g))
            else:
                routed.append((p, g))
        ops = self._optimizer.apply_gradients(routed)
        for p, master in low_prec:
            # re-derive the low-precision compute copy from the updated
            # fp32 master INSIDE the same jitted step (no device sync);
            # pruned from for_test clones with the other update ops
            block.append_op(type="cast", inputs={"X": [master]},
                            outputs={"Out": [p]},
                            attrs={"in_dtype": "float32",
                                   "out_dtype": p.dtype,
                                   "__amp_state__": True})
        return ops

    def apply_optimize(self, loss, startup_program, params_grads):
        return self.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = self.backward(loss, startup_program,
                                     parameter_list, no_grad_set)
        optimize_ops = self.apply_optimize(loss, startup_program,
                                           params_grads)
        return optimize_ops, params_grads

    # -- telemetry ---------------------------------------------------------
    def record_metrics(self, scope=None):
        """Publish the runtime loss-scaling state as amp/* gauges
        (docs/OBSERVABILITY.md) and return it as a dict. Host-side scope
        read — call at a sync point, not per step."""
        from .core.scope import global_scope

        scope = scope if scope is not None else global_scope()
        out = {}
        if self._loss_scaling is not None:
            v = scope.get(self._loss_scaling.name)
            if v is not None:
                out["loss_scale"] = float(np.asarray(v).reshape(()))
                _metrics.gauge("amp/loss_scale").set(out["loss_scale"])
        if self._overflow_steps is not None:
            v = scope.get(self._overflow_steps.name)
            if v is not None:
                out["overflow_steps"] = int(np.asarray(v).reshape(()))
                _metrics.gauge("amp/overflow_steps").set(
                    out["overflow_steps"])
        return out


def decorate(optimizer, amp_lists=None, amp_level="O1", dtype="bfloat16",
             init_loss_scaling=None, use_dynamic_loss_scaling=None,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.5, master_weight=True):
    """Wrap `optimizer` for mixed-precision training (parity:
    contrib/mixed_precision/decorator.py decorate, extended with the
    Fluid 1.8 amp_level knob).

    Defaults follow the dtype: bfloat16 shares fp32's exponent range, so
    loss scaling is OFF (scale 1.0, static); float16 turns dynamic loss
    scaling ON at 2**15. Pass explicit values to override either."""
    cfg = AmpConfig(level=amp_level, dtype=dtype, lists=amp_lists)
    f16 = cfg.dtype == "float16"
    if use_dynamic_loss_scaling is None:
        use_dynamic_loss_scaling = f16
    if init_loss_scaling is None:
        init_loss_scaling = 2.0 ** 15 if f16 else 1.0
    return AmpOptimizer(optimizer, cfg, init_loss_scaling,
                        use_dynamic_loss_scaling, incr_every_n_steps,
                        decr_every_n_nan_or_inf, incr_ratio, decr_ratio,
                        master_weight=master_weight)


# ---------------------------------------------------------------------------
# gradient bucketing (Megatron-LM DDP parity)
# ---------------------------------------------------------------------------


def mb_to_bucket_bytes(mb):
    """MiB -> bytes under the one shared convention: 0 is the documented
    off switch (None = bucketing disabled). Anything that cannot become a
    sane capacity — NaN, negative values — raises here, at the knob, so a
    typo'd $PTPU_AMP_BUCKET_MB can never propagate a NaN bucket cap into
    plan_buckets."""
    mb = float(mb)
    if np.isnan(mb) or mb < 0:
        raise ValueError(
            "bucket size %r MiB is not a valid capacity (use a positive "
            "number of MiB, or 0 to disable bucketing)" % (mb,))
    return int(mb * (1 << 20)) if mb > 0 else None


def bucket_bytes_from_env(default_mb=_DEFAULT_BUCKET_MB):
    """Bucket size in BYTES from $PTPU_AMP_BUCKET_MB (None = bucketing
    not requested when `default_mb` is None)."""
    try:
        mb = _env("PTPU_AMP_BUCKET_MB")
    except ValueError as exc:
        raise ValueError(
            "PTPU_AMP_BUCKET_MB is not a usable bucket size: %s" % (exc,))
    if mb is not None:
        try:
            return mb_to_bucket_bytes(mb)
        except ValueError as exc:
            raise ValueError(
                "PTPU_AMP_BUCKET_MB=%r is not a usable bucket size: %s"
                % (mb, exc))
    if default_mb is None:
        return None
    return mb_to_bucket_bytes(default_mb)


class Bucket:
    """One flattened same-dtype gradient bucket: leaf indices, their
    flat sizes/offsets, and the padded total length. `segment` is the
    bucket's position in the planned issue order — under backward-order
    planning (docs/ZERO.md) segment 0 is the bucket whose gradients the
    backward pass produces FIRST, i.e. the first collective the overlap
    chain may issue."""

    __slots__ = ("indices", "sizes", "offsets", "size", "padded", "dtype",
                 "segment")

    def __init__(self, dtype):
        self.indices = []
        self.sizes = []
        self.offsets = []
        self.size = 0
        self.padded = 0
        self.dtype = dtype
        self.segment = None

    def nbytes(self):
        return self.padded * _dtype_itemsize(self.dtype)


def _dtype_itemsize(dtype):
    if _is_bf16(dtype):
        return 2
    return np.dtype(dtype).itemsize


def _is_bf16(dtype):
    return "bfloat16" in str(dtype)


def plan_buckets(leaves, bucket_bytes, pad_multiple=1, dtype=None,
                 order="forward"):
    """Group `leaves` (arrays or anything with .shape/.dtype) into
    flattened buckets of at most `bucket_bytes` each (a single leaf
    larger than the cap gets its own bucket), grouped by collective
    dtype and padded to a multiple of `pad_multiple` elements. `dtype`
    forces one collective dtype for every bucket (e.g. bf16 gradients);
    None groups by each leaf's own dtype.

    `order` is the issue order the plan encodes (Bucket.segment):
    "forward" walks leaves in tree-flatten order (the PR-5 layout);
    "backward" walks them REVERSED — bucket/segment 0 then holds the
    LAST leaves, whose gradients the backward pass produces first, which
    is the order the comm/compute overlap chain wants to issue
    collectives in (docs/ZERO.md). Records amp/bucket_bytes and
    amp/buckets telemetry."""
    bb = float(bucket_bytes) if bucket_bytes is not None else float("nan")
    if np.isnan(bb) or bb <= 0:
        raise ValueError(
            "plan_buckets: bucket_bytes=%r is not a positive capacity "
            "(check bucket_mb / $PTPU_AMP_BUCKET_MB)" % (bucket_bytes,))
    if order not in ("forward", "backward"):
        raise ValueError("plan_buckets: unknown order %r" % (order,))
    groups = {}
    out = []
    walk = (reversed(list(enumerate(leaves))) if order == "backward"
            else enumerate(leaves))
    for i, leaf in walk:
        dt = dtype if dtype is not None else leaf.dtype
        key = str(dt)
        size = int(np.prod(np.shape(leaf))) if np.ndim(leaf) else 1
        item = _dtype_itemsize(dt)
        bs = groups.setdefault(key, [])
        if not bs or (bs[-1].size
                      and (bs[-1].size + size) * item > bucket_bytes):
            b = Bucket(dt)
            bs.append(b)
            out.append(b)
        b = bs[-1]
        b.indices.append(i)
        b.offsets.append(b.size)
        b.sizes.append(size)
        b.size += size
    for seg, b in enumerate(out):
        b.segment = seg
        b.padded = b.size + (-b.size) % max(int(pad_multiple), 1)
    total = sum(b.padded * _dtype_itemsize(b.dtype) for b in out)
    _metrics.gauge("amp/bucket_bytes").set(total)
    _metrics.counter("amp/buckets").inc(len(out))
    return out


def flatten_bucket(bucket, leaves, dtype=None):
    """Concatenate the bucket's leaves into one padded 1-D array in the
    bucket's collective dtype (`dtype` overrides it — e.g. fp32 for the
    master-param buffer sharing a gradient bucket's layout)."""
    import jax.numpy as jnp

    parts = [jnp.ravel(leaves[i]).astype(dtype or bucket.dtype)
             for i in bucket.indices]
    flat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    if bucket.padded > bucket.size:
        flat = jnp.pad(flat, (0, bucket.padded - bucket.size))
    return flat


def unflatten_bucket(bucket, flat, like_leaves):
    """{leaf index: array} re-slicing `flat` back into the bucket's
    leaves, reshaped to (and cast to the dtype of) `like_leaves`."""
    out = {}
    for i, off, sz in zip(bucket.indices, bucket.offsets, bucket.sizes):
        ref = like_leaves[i]
        out[i] = flat[off:off + sz].reshape(np.shape(ref)).astype(
            ref.dtype)
    return out
